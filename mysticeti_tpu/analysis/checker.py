"""The syntactic AST rules, the analysis driver, and finding/baseline machinery.

Thirteen rules total: the eight per-call-site syntactic rules implemented
here, the determinism/concurrency soundness analyses delegated to
:mod:`.detflow` (``sim-taint``), :mod:`.races` (``await-atomicity``) and
:mod:`.lockgraph` (``lock-order``, ``guard-inference``), plus the
``unused-suppression`` hygiene rule.  This module also owns the repo-level
driver (:func:`analyze_paths`): content-hash result caching, the
multiprocessing per-file pass, and the cross-file rules.

Pure stdlib (``ast``, ``json``, ``re``, ``tokenize``); no imports of the
package under analysis, so the checker runs even when optional heavy deps
(jax, numpy, prometheus_client) are absent or broken.

Every rule is deliberately *syntactic* and scoped to this codebase's idioms:
precision over generality.  A rule that cries wolf gets suppressed wholesale
and enforces nothing; each detector below accepts known-good shapes (handles
awaited in-scope, dispatch hidden behind ``run_in_executor``, casts of static
shapes) so that what remains flagged is worth a human look.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULE_ASYNC_BLOCKING = "async-blocking"
RULE_TASK_ORPHAN = "task-orphan"
RULE_LOCK_DISCIPLINE = "lock-discipline"
RULE_JIT_PURITY = "jit-purity"
RULE_WALL_CLOCK = "wall-clock"
RULE_METRICS_LABELS = "metrics-labels"
RULE_SPAN_NAMES = "span-names"
RULE_METRICS_DOC = "metrics-doc"
# Determinism/concurrency soundness plane (detflow.py, races.py,
# lockgraph.py): dataflow and lock-graph rules, not per-call-site syntax.
RULE_SIM_TAINT = "sim-taint"
RULE_AWAIT_ATOMICITY = "await-atomicity"
RULE_LOCK_ORDER = "lock-order"
RULE_GUARD_INFERENCE = "guard-inference"
# Suppression hygiene: an ignore comment must still suppress something.
RULE_UNUSED_SUPPRESSION = "unused-suppression"
# Native extension fallback contract (native/__init__.py): every call into
# the C extension must sit under a `native is None`-aware gate.
RULE_NATIVE_FALLBACK = "native-fallback"

RULES = (
    RULE_ASYNC_BLOCKING,
    RULE_TASK_ORPHAN,
    RULE_LOCK_DISCIPLINE,
    RULE_JIT_PURITY,
    RULE_WALL_CLOCK,
    RULE_METRICS_LABELS,
    RULE_SPAN_NAMES,
    RULE_METRICS_DOC,
    RULE_SIM_TAINT,
    RULE_AWAIT_ATOMICITY,
    RULE_LOCK_ORDER,
    RULE_GUARD_INFERENCE,
    RULE_UNUSED_SUPPRESSION,
    RULE_NATIVE_FALLBACK,
)

# -- rule configuration -------------------------------------------------------

# Rule 1: calls that block the event loop when made directly from a coroutine.
BLOCKING_CALLS = {
    "time.sleep",
    "os.system",
    "os.waitpid",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "socket.create_connection",
    "urllib.request.urlopen",
    "requests.get",
    "requests.post",
    "requests.request",
}
# Method names that are synchronous accelerator dispatches: a direct call in a
# coroutine stalls consensus for the whole device round-trip (the
# BatchedSignatureVerifier comment: "the device dispatch runs in a worker
# thread so the event loop never blocks").
BLOCKING_METHODS = {"verify_signatures"}

# Rule 2: task spawners whose naked handle swallows exceptions.
SPAWN_NAMES = {"ensure_future", "create_task"}
# Uses of a task handle that constitute supervision: someone will observe the
# task's exception.
_WAITER_SUFFIXES = ("wait", "wait_for", "gather", "shield")

# Rule 3b: shared fields with a designated lock (the comment-documented
# EMA/counter discipline in block_validator.py).  Mutations anywhere but
# ``__init__`` must sit lexically inside ``with self.<lock>:``.
GUARDED_FIELDS: Dict[str, str] = {
    "_dispatch_ema_s": "_lock",
    "cpu_per_sig_s": "_ema_lock",
    "tpu_dispatch_s": "_ema_lock",
    "tpu_per_sig_s": "_ema_lock",
    # Hybrid verifier circuit breaker: tripped/probed/closed from concurrent
    # dispatch threads; shares the EMA lock (same writers, same cadence).
    "_breaker_backoff_s": "_ema_lock",
    "_breaker_gen": "_ema_lock",
    "_breaker_open_until": "_ema_lock",
    "_breaker_probing": "_ema_lock",
    # Backend pin (zero-tax short-circuit routing): pinned/probed/unpinned
    # from concurrent dispatch threads; shares the EMA lock like the breaker.
    "_pinned_backend": "_ema_lock",
    "_pin_backoff_s": "_ema_lock",
    "_pin_next_probe_t": "_ema_lock",
    # Batching collector arrival-rate EMA: read-modify-written under the
    # pending-queue lock alongside the dispatch EMA it modulates.
    "_arrival_gap_ema_s": "_lock",
    "_last_arrival_t": "_lock",
    # RemoteSignatureVerifier's staged-dispatch connection pool: checked
    # out/in from any executor thread; the live-connection count must move
    # with the deque under one lock or the bound drifts.
    "_pool_size": "_pool_lock",
    # Flight-recorder event ring (flight_recorder.py): appended from the
    # loop thread while the metrics endpoint / a signal path snapshots it —
    # any reassignment (resize, swap) must happen under the ring lock.
    "_flight_ring": "_ring_lock",
    # Dissemination frame cache (synchronizer.FrameCache): the encode-once
    # entry table is read/written per push frame and carries the reuse
    # census — every mutation outside __init__ must hold the cache lock.
    # (Named distinctly from network._FrameReceiver._frames, which is
    # single-threaded by design — GUARDED_FIELDS matches globally by
    # attribute name.)
    "_frame_entries": "_frame_lock",
    # Segmented WAL manifest table (storage.py): the segment list is
    # rewritten by the appender on roll/GC/tear-truncation and read by the
    # paired reader, the metrics sampler, and the fsync thread — every
    # reassignment must happen under the table lock or a reader resolves a
    # position against a half-swapped table.
    "_segments": "_seg_lock",
    # Ingress mempool accounting (ingress.Mempool): the pool's aggregate
    # transaction/byte counters move with the lane deques — submissions may
    # arrive from application threads while the core drains on the loop, so
    # every read-modify-write must hold the mempool lock or the caps drift.
    "_mempool_count": "_mempool_lock",
    "_mempool_bytes": "_mempool_lock",
    # Ingress admission token bucket (ingress.AdmissionController): admit()
    # rides the thread-capable submit path while tick() adjusts the rate on
    # the loop — an unguarded spend would let two concurrent admits both
    # read the same balance and double the admitted rate.
    "_tokens": "_lock",
    # Subsystem accountant (profiling.SubsystemAccountant): the sampler
    # thread ingests the census while publish()/report() read from the
    # loop or a shutdown path — every counter mutation must hold the
    # accountant lock or a publish() mid-ingest exports a torn delta.
    "_cpu_seconds": "_acct_lock",
    "_census_ticks": "_acct_lock",
    "_convoy_ticks": "_acct_lock",
    "_runnable_sum": "_acct_lock",
    # Commit-decision ledger (decisions.DecisionLedger): the loop thread
    # appends records during try_commit while the metrics endpoint serves
    # /debug/consensus and tools snapshot the canonical ledger bytes —
    # ring, flip-detection key set, and frontier tuple all move together
    # under the decision lock or a snapshot reads a torn ledger.
    "_decision_ring": "_decision_lock",
    "_undecided_keys": "_decision_lock",
    "_undecided_slots": "_decision_lock",
    # Finality SLI joiner (finality.FinalityTracker): lifecycle stamps
    # arrive from the thread-capable submit path, the loop's proposal
    # drain, and the commit observer while the ingress tick reads
    # percentiles — pending table and sample window share one lock.
    # (ClientFinalityRecorder deliberately uses different field names —
    # it is loop-thread-only and lock-free by design.)
    "_finality_pending": "_finality_lock",
    "_finality_samples": "_finality_lock",
    # Execution account table (execution.ExecutionState): the core's commit
    # fold mutates balances on the loop thread while ingress submit threads
    # probe admission verdicts and checkpoint writers serialize the table —
    # every reassignment/mutation outside __init__ must hold the execution
    # lock or an admission probe reads a half-applied transfer.
    "_exec_accounts": "_exec_lock",
}

# Rule 4: directories whose jitted functions must stay trace-pure.
JIT_PURITY_DIRS = ("ops", "parallel")
JIT_IMPURE_CALLS = {
    "jax.debug.print",
    "jax.debug.breakpoint",
}
JIT_IMPURE_PREFIXES = ("numpy.", "time.")

# Rule 7: span-tracer call surface.  A stage-name typo at an instrumentation
# site silently splits (begin under one name, end under another: the span
# never closes) — every literal stage must come from spans.STAGES.
SPAN_CALL_NAMES = {"span", "begin_span", "end_span", "record_span"}

_IGNORE_RE = re.compile(r"#\s*lint:\s*ignore(?!-module)(?:\[([A-Za-z0-9_,\- ]+)\])?")
# Whole-module opt-out for rules whose premise a module structurally
# escapes (e.g. sim-taint on a socket-plane module that can never run
# under the simulator: _NullSelector refuses the registration).  Placed
# at the top of the module with its justification; exempt from
# unused-suppression (it states an architectural fact, not a finding).
_IGNORE_MODULE_RE = re.compile(r"#\s*lint:\s*ignore-module\[([A-Za-z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    # Additional lines where an inline suppression also silences this
    # finding (e.g. a sim-taint finding is suppressible at its *source*
    # read, not only at the sink).  Not part of identity.
    also_lines: Tuple[int, ...] = ()

    def fingerprint(self) -> str:
        """Line-independent identity used by the baseline: survives pure
        line-number drift, invalidates when the code itself changes."""
        return f"{self.rule}|{self.path}|{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


def _dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" with the leading segment resolved through import
    aliases (``import numpy as np`` makes ``np.x`` -> "numpy.x")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _is_lock_ctor(call: ast.AST, aliases: Dict[str, str]) -> bool:
    if not isinstance(call, ast.Call):
        return False
    dotted = _dotted(call.func, aliases)
    return dotted in {"threading.Lock", "threading.RLock"}


def _collect_class_locks(
    cls: ast.ClassDef, aliases: Dict[str, str]
) -> Set[str]:
    """Attribute names assigned a ``threading.Lock()`` anywhere in the class."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value, aliases):
            for target in node.targets:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    locks.add(target.attr)
    return locks


def _collect_jit_targets(tree: ast.Module, aliases: Dict[str, str]) -> Set[str]:
    """Function names compiled indirectly: ``k = jax.jit(fn)`` and pallas
    kernels (``pl.pallas_call(fn, ...)``)."""
    targets: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func, aliases) or ""
        if dotted in {"jax.jit", "jit"} and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                targets.add(arg.id)
        if dotted.endswith("pallas_call") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                targets.add(arg.id)
    return targets


def _is_jit_decorated(fn: ast.AST, aliases: Dict[str, str]) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for deco in fn.decorator_list:
        dotted = _dotted(deco, aliases)
        if dotted in {"jax.jit", "jit"}:
            return True
        if isinstance(deco, ast.Call):
            dotted = _dotted(deco.func, aliases)
            if dotted in {"jax.jit", "jit"}:
                return True
            if dotted in {"functools.partial", "partial"} and deco.args:
                inner = _dotted(deco.args[0], aliases)
                if inner in {"jax.jit", "jit"}:
                    return True
    return False


def collect_metric_labels(tree: ast.Module) -> Dict[str, Tuple[str, ...]]:
    """Declared label tuples per series attribute, from metrics.py's
    ``self.X = counter/gauge/histogram(name, doc, labels=(...))`` idiom (and
    raw prometheus_client constructors with ``labelnames=``)."""
    declared: Dict[str, Tuple[str, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        func = call.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name not in {
            "counter", "gauge", "histogram", "Counter", "Gauge", "Histogram",
        }:
            continue
        labels: Tuple[str, ...] = ()
        for kw in call.keywords:
            if kw.arg in {"labels", "labelnames"}:
                if isinstance(kw.value, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in kw.value.elts
                ):
                    labels = tuple(e.value for e in kw.value.elts)
                else:
                    labels = ("<dynamic>",)
        if labels == ("<dynamic>",):
            continue  # computed label list: not statically checkable, skip
        for target in node.targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                declared[target.attr] = labels
            elif isinstance(target, ast.Name):
                declared[target.id] = labels
    return declared


def collect_metric_names(tree: ast.Module) -> Dict[str, int]:
    """Registered series name -> registration line, from metrics.py's
    ``counter/gauge/histogram("name", ...)`` idiom (and raw
    prometheus_client constructors).  The benchmark-defining series are
    registered through module-level string constants
    (``counter(BENCHMARK_DURATION, ...)``) — those names resolve too."""
    consts: Dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if isinstance(node.value, ast.Constant) and isinstance(
            node.value.value, str
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    consts[target.id] = node.value.value
    names: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = None
        if isinstance(func, ast.Name):
            fname = func.id
        elif isinstance(func, ast.Attribute):
            fname = func.attr
        if fname not in {
            "counter", "gauge", "histogram", "Counter", "Gauge", "Histogram",
        }:
            continue
        if not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            names.setdefault(first.value, node.lineno)
        elif isinstance(first, ast.Name) and first.id in consts:
            names.setdefault(consts[first.id], node.lineno)
    return names


# Series tokens in the observability doc: a prometheus metric name, possibly
# wildcarded (``mysticeti_health_*`` names the family, not a series).  The
# package itself shares the prefix — ``mysticeti_tpu`` (as in
# ``python -m mysticeti_tpu`` or a module path) is never a series name.
_DOC_SERIES_RE = re.compile(r"\bmysticeti_[a-z0-9_]+\b")
_DOC_SERIES_NOT = frozenset({"mysticeti_tpu"})


def check_metrics_doc(
    metric_names: Dict[str, int],
    metrics_path: str,
    doc_text: str,
    doc_path: str,
) -> List[Finding]:
    """The ``metrics-doc`` rule: every series registered in metrics.py must
    appear in docs/observability.md (the doc is the series inventory of
    record), and every ``mysticeti_*`` series the doc names must actually be
    registered (no documenting what was renamed away).  Cross-file, so it
    runs at the repo level rather than per-module."""
    findings: List[Finding] = []
    # Direction 1: registered but undocumented.  Token match (word
    # boundaries) so ``latency_s`` does not ride on ``latency_squared_s``.
    for name in sorted(metric_names):
        if not re.search(rf"\b{re.escape(name)}\b", doc_text):
            findings.append(
                Finding(
                    RULE_METRICS_DOC,
                    metrics_path,
                    metric_names[name],
                    0,
                    f"series '{name}' is registered in metrics.py but "
                    f"missing from {doc_path} (the series inventory of "
                    "record; add a row or drop the series)",
                )
            )
    # Direction 2: documented mysticeti_* series that no longer exist.
    registered = set(metric_names)
    for lineno, line in enumerate(doc_text.splitlines(), start=1):
        for match in _DOC_SERIES_RE.finditer(line):
            token = match.group(0)
            if token.endswith("_") or token in _DOC_SERIES_NOT:
                continue  # family wildcard / the package's own name
            if token not in registered:
                findings.append(
                    Finding(
                        RULE_METRICS_DOC,
                        doc_path,
                        lineno,
                        match.start(),
                        f"doc names series '{token}' which is not "
                        "registered in metrics.py (renamed or removed? "
                        "update the inventory)",
                    )
                )
    return findings


def collect_span_stages(tree: ast.Module) -> Optional[Tuple[str, ...]]:
    """The central stage registry from spans.py's ``STAGES = ("...", ...)``
    literal-tuple assignment (kept literal precisely so this parse works)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id == "STAGES":
                if isinstance(node.value, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in node.value.elts
                ):
                    return tuple(e.value for e in node.value.elts)
    return None


def comment_lines(source: str) -> Dict[int, str]:
    """line -> comment text, via the tokenizer: a ``# lint: ...`` pattern
    quoted inside a docstring or message string is prose *about* the
    directive, not the directive — only real comments count."""
    import io
    import tokenize

    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Unterminated construct mid-file: degrade to the raw-line scan.
        for i, line in enumerate(source.splitlines(), start=1):
            if "#" in line:
                out[i] = line
    return out


def _suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule set (None = all rules)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in comment_lines(source).items():
        m = _IGNORE_RE.search(line)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = {part.strip() for part in m.group(1).split(",") if part.strip()}
    return out


class _FunctionScope:
    """Per-function bookkeeping for the task-orphan and wall-clock rules."""

    __slots__ = (
        "node", "is_async", "spawns", "awaited", "returned", "callbacked",
        "waited", "wall_names",
    )

    def __init__(self, node: Optional[ast.AST], is_async: bool) -> None:
        self.node = node
        self.is_async = is_async
        # (call node, binding) — binding is the assigned name/attr dotted
        # string, "" for a bare-expression spawn, None for compliant shapes.
        self.spawns: List[Tuple[ast.Call, Optional[str]]] = []
        self.awaited: Set[str] = set()
        self.returned: Set[str] = set()
        self.callbacked: Set[str] = set()
        self.waited: Set[str] = set()
        self.wall_names: Set[str] = set()


class _Checker(ast.NodeVisitor):
    def __init__(
        self,
        path: str,
        aliases: Dict[str, str],
        jit_targets: Set[str],
        metric_labels: Optional[Dict[str, Tuple[str, ...]]],
        span_stages: Optional[Tuple[str, ...]] = None,
    ) -> None:
        self.path = path
        self.aliases = aliases
        self.jit_targets = jit_targets
        self.metric_labels = metric_labels
        self.span_stages = span_stages
        self.findings: List[Finding] = []
        self._scopes: List[_FunctionScope] = [_FunctionScope(None, False)]
        self._class_locks: List[Set[str]] = []
        self._held_locks: List[str] = []
        self._method: List[str] = []
        norm = path.replace(os.sep, "/")
        self._jit_dir = any(f"/{d}/" in f"/{norm}" for d in JIT_PURITY_DIRS)

    # -- helpers --

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(rule, self.path, node.lineno, node.col_offset, message)
        )

    def _dot(self, node: ast.AST) -> Optional[str]:
        return _dotted(node, self.aliases)

    @property
    def _scope(self) -> _FunctionScope:
        return self._scopes[-1]

    def _is_spawn(self, call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Name):
            resolved = self.aliases.get(func.id, func.id)
            return resolved.rsplit(".", 1)[-1] in SPAWN_NAMES
        if isinstance(func, ast.Attribute):
            return func.attr in SPAWN_NAMES
        return False

    # -- scope / class structure --

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_locks.append(_collect_class_locks(node, self.aliases))
        self.generic_visit(node)
        self._class_locks.pop()

    def _visit_function(self, node, is_async: bool) -> None:
        jitted = self._jit_dir and (
            node.name in self.jit_targets or _is_jit_decorated(node, self.aliases)
        )
        self._scopes.append(_FunctionScope(node, is_async))
        self._method.append(node.name)
        held, self._held_locks = self._held_locks, []
        for stmt in node.body:
            self.visit(stmt)
        self._held_locks = held
        self._method.pop()
        scope = self._scopes.pop()
        self._finish_scope(scope)
        if jitted:
            self._check_jit_purity(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_function(node, is_async=True)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A lambda's value is returned to its caller; ``call_later(...,
        # lambda: ensure_future(c))`` discards the handle, so a spawn that IS
        # the whole lambda body is an orphan.
        body = node.body
        if isinstance(body, ast.Call) and self._is_spawn(body):
            self._scope.spawns.append((body, ""))
            for arg in ast.iter_child_nodes(body):
                self.visit(arg)
        else:
            self.generic_visit(node)

    # -- statement-level contexts for the task-orphan rule --

    def visit_Expr(self, node: ast.Expr) -> None:
        value = node.value
        if isinstance(value, ast.Call) and self._is_spawn(value):
            self._scope.spawns.append((value, ""))
            for child in ast.iter_child_nodes(value):
                self.visit(child)
            return
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        bindings: List[Optional[str]] = []
        spawn_nodes: List[ast.Call] = []
        if isinstance(value, ast.Call) and self._is_spawn(value):
            spawn_nodes = [value]
        elif isinstance(value, (ast.List, ast.Tuple)):
            spawn_nodes = [
                e for e in value.elts
                if isinstance(e, ast.Call) and self._is_spawn(e)
            ]
        if spawn_nodes:
            target = node.targets[0]
            binding: Optional[str] = None
            if isinstance(target, ast.Name):
                binding = target.id
            elif isinstance(target, ast.Attribute):
                binding = self._dot(target)
            for spawn in spawn_nodes:
                self._scope.spawns.append((spawn, binding))
            for spawn in spawn_nodes:
                for child in ast.iter_child_nodes(spawn):
                    self.visit(child)
            for other in ast.iter_child_nodes(node):
                if other is not value:
                    self.visit(other)
            self._note_wall_assign(node)
            return
        self._note_wall_assign(node)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        value = node.value
        if isinstance(value, ast.Call) and self._is_spawn(value):
            self._scope.spawns.append((value, None))  # handed to the caller
            for child in ast.iter_child_nodes(value):
                self.visit(child)
            return
        if isinstance(value, ast.Name):
            self._scope.returned.add(value.id)
        elif isinstance(value, ast.Attribute):
            dotted = self._dot(value)
            if dotted:
                self._scope.returned.add(dotted)
        self.generic_visit(node)

    def visit_Await(self, node: ast.Await) -> None:
        value = node.value
        if self._held_locks:
            self._emit(
                RULE_LOCK_DISCIPLINE,
                node,
                f"await while holding threading lock '{self._held_locks[-1]}' "
                "(blocks the event loop; use the lock only around non-awaiting "
                "critical sections)",
            )
        if isinstance(value, ast.Call) and self._is_spawn(value):
            self._scope.spawns.append((value, None))  # awaited immediately
            for child in ast.iter_child_nodes(value):
                self.visit(child)
            return
        if isinstance(value, ast.Name):
            self._scope.awaited.add(value.id)
        elif isinstance(value, ast.Attribute):
            dotted = self._dot(value)
            if dotted:
                self._scope.awaited.add(dotted)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        lock_attrs = self._class_locks[-1] if self._class_locks else set()
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in lock_attrs
            ):
                self._held_locks.append(expr.attr)
                pushed += 1
        for item in node.items:
            self.visit(item)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self._held_locks.pop()

    # -- calls: blocking-in-async, metrics labels, spawn args, callbacks --

    def visit_Call(self, node: ast.Call) -> None:
        dotted = self._dot(node.func) or ""
        func = node.func

        if isinstance(func, ast.Attribute):
            if func.attr == "add_done_callback":
                owner = self._dot(func.value)
                if owner:
                    self._scope.callbacked.add(owner)
            if func.attr == "labels":
                self._check_metric_labels(node, func)
            if func.attr == "append" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call) and self._is_spawn(arg):
                    # Appending straight into a task list stores the handle
                    # but nobody ever awaits list members — exceptions are
                    # swallowed until (at best) interpreter shutdown.
                    self._scope.spawns.append((arg, ""))
                    for child in ast.iter_child_nodes(arg):
                        self.visit(child)
                    for other in node.args[1:] + [kw.value for kw in node.keywords]:
                        self.visit(other)
                    self.visit(func.value)
                    return

        self._check_span_name(node)

        if self._scope.is_async:
            self._check_async_blocking(node, dotted)

        tail = dotted.rsplit(".", 1)[-1]
        if tail in _WAITER_SUFFIXES:
            for arg in node.args:
                self._note_waited(arg)

        self._check_wall_clock_call(node)
        self.generic_visit(node)

    def _note_waited(self, arg: ast.AST) -> None:
        if isinstance(arg, ast.Name):
            self._scope.waited.add(arg.id)
        elif isinstance(arg, ast.Attribute):
            dotted = self._dot(arg)
            if dotted:
                self._scope.waited.add(dotted)
        elif isinstance(arg, (ast.List, ast.Tuple, ast.Set)):
            for e in arg.elts:
                self._note_waited(e)
        elif isinstance(arg, ast.Starred):
            self._note_waited(arg.value)

    def _check_async_blocking(self, node: ast.Call, dotted: str) -> None:
        if dotted in BLOCKING_CALLS:
            self._emit(
                RULE_ASYNC_BLOCKING,
                node,
                f"blocking call {dotted}() inside async def "
                f"{self._method[-1] if self._method else '<module>'} "
                "(use asyncio equivalents or run_in_executor)",
            )
            return
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in BLOCKING_METHODS:
            self._emit(
                RULE_ASYNC_BLOCKING,
                node,
                f"synchronous accelerator dispatch .{func.attr}() called "
                "directly from a coroutine (dispatch via run_in_executor so "
                "the event loop never blocks on the device)",
            )

    # -- rule 3b: guarded-field mutation --

    def _check_guarded_target(self, target: ast.AST, node: ast.AST) -> None:
        if not (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and target.attr in GUARDED_FIELDS
        ):
            return
        if self._method and self._method[-1] == "__init__":
            return
        lock = GUARDED_FIELDS[target.attr]
        if lock not in self._held_locks:
            self._emit(
                RULE_LOCK_DISCIPLINE,
                node,
                f"shared field self.{target.attr} mutated outside its "
                f"designated lock 'self.{lock}' (EMA/counter read-modify-"
                "writes race across threads)",
            )

    # -- rule 5: wall-clock intervals --

    def _note_wall_assign(self, node: ast.Assign) -> None:
        value = node.value
        if (
            isinstance(value, ast.Call)
            and self._dot(value.func) == "time.time"
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self._scope.wall_names.add(target.id)

    def _is_wall_operand(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call) and self._dot(node.func) == "time.time":
            return True
        return isinstance(node, ast.Name) and node.id in self._scope.wall_names

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, ast.Sub) and (
            self._is_wall_operand(node.left) or self._is_wall_operand(node.right)
        ):
            self._emit(
                RULE_WALL_CLOCK,
                node,
                "interval measured with time.time() (wall clock steps under "
                "NTP; use time.monotonic() for durations)",
            )
        self.generic_visit(node)

    def _check_wall_clock_call(self, node: ast.Call) -> None:
        # AugAssign path (``acc -= time.time()``) is rare enough to skip; the
        # assign+subtract idiom above covers this codebase.
        return

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_guarded_target(node.target, node)
        self.generic_visit(node)

    def _visit_assign_guarded(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_guarded_target(target, node)

    # -- rule 4: jit purity --

    def _check_jit_purity(self, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr == "item" and not node.args:
                self._emit(
                    RULE_JIT_PURITY,
                    node,
                    ".item() inside a jit/pallas kernel forces a host sync "
                    "per element (keep values on device)",
                )
                continue
            dotted = self._dot(func) or ""
            if dotted in JIT_IMPURE_CALLS:
                self._emit(
                    RULE_JIT_PURITY,
                    node,
                    f"{dotted}() inside a jit/pallas kernel (debug prints "
                    "recompile and serialize the kernel; gate behind "
                    "interpret mode)",
                )
            elif any(dotted.startswith(p) for p in JIT_IMPURE_PREFIXES):
                self._emit(
                    RULE_JIT_PURITY,
                    node,
                    f"host call {dotted}() inside a jit/pallas kernel "
                    "(numpy/time run at trace time, not on device — use "
                    "jax.numpy or hoist out of the kernel)",
                )
            elif isinstance(func, ast.Name) and func.id == "print":
                self._emit(
                    RULE_JIT_PURITY,
                    node,
                    "print() inside a jit/pallas kernel executes at trace "
                    "time only (use jax.debug.print in interpret mode if "
                    "needed)",
                )

    # -- rule 7: span stage names --

    def _check_span_name(self, node: ast.Call) -> None:
        if self.span_stages is None:
            return
        func = node.func
        name = None
        if isinstance(func, ast.Attribute):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name not in SPAN_CALL_NAMES or not node.args:
            return
        first = node.args[0]
        if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
            return  # computed stage: not statically checkable, skip
        if first.value not in self.span_stages:
            self._emit(
                RULE_SPAN_NAMES,
                node,
                f"span stage '{first.value}' is not in the central registry "
                "spans.STAGES (a typo'd stage silently never matches its "
                "begin/end and disappears from traces)",
            )

    # -- rule 6: metrics label arity --

    def _check_metric_labels(self, node: ast.Call, func: ast.Attribute) -> None:
        if self.metric_labels is None:
            return
        owner = func.value
        metric = None
        if isinstance(owner, ast.Attribute):
            metric = owner.attr
        elif isinstance(owner, ast.Name):
            metric = owner.id
        if metric is None or metric not in self.metric_labels:
            return
        declared = self.metric_labels[metric]
        given = len(node.args) + len(node.keywords)
        kw_names = {kw.arg for kw in node.keywords if kw.arg}
        if given != len(declared) or not kw_names.issubset(set(declared)):
            self._emit(
                RULE_METRICS_LABELS,
                node,
                f".labels() arity mismatch for series '{metric}': declared "
                f"{list(declared)} in metrics.py, call passes {given} "
                "label(s)",
            )

    # -- scope wrap-up --

    def _finish_scope(self, scope: _FunctionScope) -> None:
        supervised = scope.awaited | scope.returned | scope.callbacked | scope.waited
        for call, binding in scope.spawns:
            if binding is None:
                continue  # awaited/returned at the spawn site
            if binding and binding in supervised:
                continue
            where = f"bound to '{binding}'" if binding else "with a discarded handle"
            self.findings.append(
                Finding(
                    RULE_TASK_ORPHAN,
                    self.path,
                    call.lineno,
                    call.col_offset,
                    f"fire-and-forget task {where}: the handle is never "
                    "awaited and has no exception-logging done-callback — "
                    "exceptions are silently swallowed (use "
                    "utils.tasks.spawn_logged)",
                )
            )

    # Route Assign through both the spawn tracking above and rule 3b.
    def generic_visit(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign):
            self._visit_assign_guarded(node)
        super().generic_visit(node)


def _module_ignores(source: str) -> Set[str]:
    out: Set[str] = set()
    for line in comment_lines(source).values():
        m = _IGNORE_MODULE_RE.search(line)
        if m:
            out.update(
                part.strip() for part in m.group(1).split(",") if part.strip()
            )
    return out


@dataclass
class FileAnalysis:
    """Raw per-module analysis: findings before suppression, plus the
    lock census analyze_paths merges for the repo-level rules."""

    path: str
    findings: List[Finding]
    locks: "object"  # lockgraph.ModuleLocks (kept loose for serialization)
    suppressions: Dict[int, Optional[Set[str]]]
    module_ignores: Set[str]


def _collect_native_aliases(tree: ast.Module) -> Set[str]:
    """Module-level names bound to the native extension object via
    ``from .native import native [as X]`` (or the absolute form)."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        mod = node.module or ""
        if mod != "native" and not mod.endswith(".native"):
            continue
        for a in node.names:
            if a.name == "native":
                aliases.add(a.asname or a.name)
    return aliases


def _native_gate_polarity(test: ast.expr, alias: str) -> Optional[str]:
    """Which branch of an ``if`` with this test is native-gated for ``alias``.

    Returns ``"body"`` (``alias is not None`` / ``hasattr(alias, ...)``),
    ``"orelse"`` (``alias is None`` — the early-return shape), or ``None``.
    The comparison may sit inside a ``boolop`` conjunction
    (``if native is not None and end > 0:`` — wal.py's idiom); the scan is
    deliberately syntactic, mirroring how the contract is written at every
    existing call site.
    """
    for sub in ast.walk(test):
        if isinstance(sub, ast.Compare) and len(sub.ops) == 1:
            left, op, right = sub.left, sub.ops[0], sub.comparators[0]
            sides = (left, right)
            has_alias = any(
                isinstance(s, ast.Name) and s.id == alias for s in sides
            )
            has_none = any(
                isinstance(s, ast.Constant) and s.value is None for s in sides
            )
            if has_alias and has_none:
                if isinstance(op, (ast.IsNot, ast.NotEq)):
                    return "body"
                if isinstance(op, (ast.Is, ast.Eq)):
                    return "orelse"
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "hasattr"
            and sub.args
            and isinstance(sub.args[0], ast.Name)
            and sub.args[0].id == alias
        ):
            return "body"
    return None


# Statement fields holding nested statement lists — skipped by the
# expression scan, recursed into by the block walk.
_STMT_BLOCK_FIELDS = frozenset({"body", "orelse", "finalbody", "handlers"})


def check_native_fallback(tree: ast.Module, path: str) -> List[Finding]:
    """The ``native-fallback`` rule: every ``native.<fn>`` attribute access
    on a module alias of the C extension must sit under a
    ``native is None``-aware branch (or module-level gate) so the
    pure-Python fallback path exists — the contract ``native/__init__.py``
    documents (the extension is an acceleration, never a hard dependency;
    ``MYSTICETI_NO_NATIVE=1`` must always work).

    Scope: direct accesses through a module alias (``from .native import
    native as X`` → ``X.fn``).  Indirection through instance attributes
    (committee.py stores the module on ``self``) is the storing class's
    contract — the assignment itself is still checked here.
    Recognized gates: an enclosing ``if X is not None:`` /
    ``hasattr(X, ...)`` branch, the ``else`` of ``if X is None:``, or the
    statements following an ``if X is None: return/raise/continue`` early
    exit.
    """
    aliases = _collect_native_aliases(tree)
    if not aliases:
        return []
    findings: List[Finding] = []

    def scan_exprs(node: ast.AST, guarded: Set[str]) -> None:
        for field, value in ast.iter_fields(node):
            if field in _STMT_BLOCK_FIELDS:
                continue
            items = value if isinstance(value, list) else [value]
            for item in items:
                if not isinstance(item, ast.AST):
                    continue
                for sub in ast.walk(item):
                    if (
                        isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id in aliases
                        and sub.value.id not in guarded
                    ):
                        findings.append(
                            Finding(
                                RULE_NATIVE_FALLBACK,
                                path,
                                sub.lineno,
                                sub.col_offset,
                                f"native access '{sub.value.id}.{sub.attr}' "
                                f"outside a '{sub.value.id} is None'-aware "
                                "gate — every native call site needs a "
                                "pure-Python fallback branch "
                                "(native/__init__.py contract; gate with "
                                f"'if {sub.value.id} is not None:' or "
                                "hasattr)",
                            )
                        )

    def walk_block(stmts: Sequence[ast.stmt], guarded: Set[str]) -> None:
        flowing = set(guarded)
        for st in stmts:
            if isinstance(st, ast.If):
                scan_exprs(st.test, flowing)
                gates = {
                    a: _native_gate_polarity(st.test, a) for a in aliases
                }
                walk_block(
                    st.body,
                    flowing | {a for a, p in gates.items() if p == "body"},
                )
                walk_block(
                    st.orelse,
                    flowing | {a for a, p in gates.items() if p == "orelse"},
                )
                if st.body and isinstance(
                    st.body[-1], (ast.Return, ast.Raise, ast.Continue,
                                  ast.Break)
                ):
                    # `if X is None: return fallback` — everything after
                    # the early exit runs native-gated.
                    flowing |= {a for a, p in gates.items() if p == "orelse"}
                continue
            scan_exprs(st, flowing)
            for field in ("body", "orelse", "finalbody"):
                sub_block = getattr(st, field, None)
                if sub_block:
                    walk_block(sub_block, flowing)
            for handler in getattr(st, "handlers", ()) or ():
                walk_block(handler.body, flowing)
        return

    walk_block(tree.body, set())
    return findings


def _analyze_module(
    source: str,
    path: str,
    metric_labels: Optional[Dict[str, Tuple[str, ...]]] = None,
    span_stages: Optional[Tuple[str, ...]] = None,
) -> FileAnalysis:
    """Run every per-module rule; suppressions are recorded, not applied."""
    from . import detflow, lockgraph, races

    tree = ast.parse(source, filename=path)
    aliases = _collect_aliases(tree)
    jit_targets = _collect_jit_targets(tree, aliases)
    checker = _Checker(path, aliases, jit_targets, metric_labels, span_stages)
    # Rule 3b must also see module-level and __init__ assigns routed through
    # generic_visit; the NodeVisitor dispatch handles the rest.
    checker.visit(tree)
    findings = list(checker.findings)
    ignores = _module_ignores(source)

    if RULE_SIM_TAINT not in ignores:
        for tf in detflow.check_sim_taint(tree, aliases):
            findings.append(
                Finding(
                    RULE_SIM_TAINT, path, tf.line, tf.col, tf.message,
                    also_lines=(tf.source_line,) if tf.source_line else (),
                )
            )
    if RULE_AWAIT_ATOMICITY not in ignores:
        for rf in races.check_await_atomicity(tree, aliases, source):
            findings.append(
                Finding(RULE_AWAIT_ATOMICITY, path, rf.line, rf.col, rf.message)
            )
    if RULE_NATIVE_FALLBACK not in ignores:
        findings.extend(check_native_fallback(tree, path))
    locks = lockgraph.collect_module_locks(tree, aliases, path, source)
    if RULE_GUARD_INFERENCE not in ignores:
        for gf in lockgraph.check_guard_inference(locks, GUARDED_FIELDS):
            findings.append(
                Finding(RULE_GUARD_INFERENCE, path, gf.line, gf.col, gf.message)
            )

    return FileAnalysis(
        path=path,
        findings=[f for f in findings if f.rule not in ignores],
        locks=locks,
        suppressions=_suppressions(source),
        module_ignores=ignores,
    )


def _apply_suppressions(
    findings: Sequence[Finding],
    suppressions: Dict[int, Optional[Set[str]]],
) -> Tuple[List[Finding], Set[int]]:
    """Drop suppressed findings; return (kept, comment lines that fired).

    A finding is silenced by a matching ignore comment on its own line,
    the line above, or (when the finding carries ``also_lines`` — the
    sim-taint source read) any of those lines or the line above them.
    """
    kept: List[Finding] = []
    used: Set[int] = set()
    for f in findings:
        hit_line: Optional[int] = None
        for anchor in (f.line, *f.also_lines):
            for line in (anchor, anchor - 1):
                if line in suppressions:
                    rules = suppressions[line]
                    if rules is None or f.rule in rules:
                        hit_line = line
                    break
            if hit_line is not None:
                break
        if hit_line is None:
            kept.append(f)
        else:
            used.add(hit_line)
    return kept, used


def _unused_suppression_findings(
    path: str,
    suppressions: Dict[int, Optional[Set[str]]],
    used: Set[int],
) -> List[Finding]:
    out: List[Finding] = []
    for line, rules in sorted(suppressions.items()):
        if line in used:
            continue
        what = "all rules" if rules is None else ", ".join(sorted(rules))
        out.append(
            Finding(
                RULE_UNUSED_SUPPRESSION,
                path,
                line,
                0,
                f"suppression '# lint: ignore[{what}]' no longer matches any "
                "finding — the bug it excused is gone (or the comment "
                "drifted); delete it so suppressions cannot outlive their "
                "justification",
            )
        )
    return out


def analyze_source(
    source: str,
    path: str,
    metric_labels: Optional[Dict[str, Tuple[str, ...]]] = None,
    span_stages: Optional[Tuple[str, ...]] = None,
) -> List[Finding]:
    """Run all per-module rules over one source; returns findings with
    inline ``# lint: ignore[...]`` suppressions already applied and
    unused suppressions reported."""
    fa = _analyze_module(source, path, metric_labels, span_stages)
    kept, used = _apply_suppressions(fa.findings, fa.suppressions)
    kept.extend(_unused_suppression_findings(path, fa.suppressions, used))
    return sorted(kept, key=lambda f: (f.path, f.line, f.col, f.rule))


def analyze_file(
    path: str,
    root: Optional[str] = None,
    metric_labels: Optional[Dict[str, Tuple[str, ...]]] = None,
    span_stages: Optional[Tuple[str, ...]] = None,
) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(path, root) if root else path
    return analyze_source(
        source, rel.replace(os.sep, "/"), metric_labels, span_stages
    )


# -- per-file cache + parallel gate -------------------------------------------
#
# The repo gate runs inside tier-1 on every test invocation; with the
# dataflow rules the per-file pass is no longer trivially cheap.  Two
# levers keep it off the critical path: a content-hash cache (a file whose
# bytes and analysis toolchain are unchanged re-uses its raw findings) and
# per-file multiprocessing for the misses.  Raw (pre-suppression)
# results are cached so the repo-level rules and suppression hygiene can
# still run over the merged set.

CACHE_BASENAME = ".lint-cache.json"

_tool_fp_cache: Optional[str] = None


def _tool_fingerprint() -> str:
    """Digest of the analysis package itself: edit a rule, drop the cache."""
    global _tool_fp_cache
    if _tool_fp_cache is None:
        import hashlib

        h = hashlib.sha256()
        pkg_dir = os.path.dirname(os.path.abspath(__file__))
        for name in sorted(os.listdir(pkg_dir)):
            if name.endswith(".py"):
                with open(os.path.join(pkg_dir, name), "rb") as fh:
                    h.update(name.encode())
                    h.update(fh.read())
        _tool_fp_cache = h.hexdigest()
    return _tool_fp_cache


def _entry_key(source: str, context_fp: str) -> str:
    import hashlib

    h = hashlib.sha256()
    h.update(source.encode("utf-8", "surrogatepass"))
    h.update(_tool_fingerprint().encode())
    h.update(context_fp.encode())
    return h.hexdigest()


def _serialize_analysis(fa: FileAnalysis) -> dict:
    locks = fa.locks
    return {
        "findings": [
            [f.rule, f.line, f.col, f.message, list(f.also_lines)]
            for f in fa.findings
        ],
        "edges": [
            [e.held, e.acquired, e.path, e.line] for e in locks.edges
        ],
        "writes": [
            [
                cls,
                attr,
                census.guarded,
                [[line, col, sorted(held)] for line, col, held in census.sites],
                sorted(census.touched),
            ]
            for (cls, attr), census in sorted(locks.writes.items())
        ],
        "suppressions": {
            str(line): (None if rules is None else sorted(rules))
            for line, rules in fa.suppressions.items()
        },
        "module_ignores": sorted(fa.module_ignores),
    }


def _deserialize_analysis(path: str, data: dict) -> FileAnalysis:
    from .lockgraph import FieldWrites, LockEdge, ModuleLocks

    locks = ModuleLocks()
    locks.edges = [
        LockEdge(held, acquired, epath, line)
        for held, acquired, epath, line in data["edges"]
    ]
    for cls, attr, guarded, sites, touched in data["writes"]:
        census = FieldWrites()
        census.guarded = {str(k): int(v) for k, v in guarded.items()}
        census.sites = [
            (line, col, frozenset(held)) for line, col, held in sites
        ]
        census.touched = set(touched)
        locks.writes[(cls, attr)] = census
    return FileAnalysis(
        path=path,
        findings=[
            Finding(rule, path, line, col, message, also_lines=tuple(also))
            for rule, line, col, message, also in data["findings"]
        ],
        locks=locks,
        suppressions={
            int(line): (None if rules is None else set(rules))
            for line, rules in data["suppressions"].items()
        },
        module_ignores=set(data["module_ignores"]),
    )


def _pool_worker(args: Tuple) -> Tuple[str, dict]:
    """Module-level so multiprocessing can pickle it."""
    rel, source, metric_labels, span_stages = args
    fa = _analyze_module(source, rel, metric_labels, span_stages)
    return rel, _serialize_analysis(fa)


def _load_cache(cache_path: str) -> Dict[str, dict]:
    try:
        with open(cache_path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    entries = data.get("entries")
    return entries if isinstance(entries, dict) else {}


def _store_cache(cache_path: str, entries: Dict[str, dict]) -> None:
    tmp = f"{cache_path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"entries": entries}, fh)
        os.replace(tmp, cache_path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if not d.startswith((".", "__pycache__"))]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def analyze_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    jobs: Optional[int] = None,
    use_cache: bool = True,
) -> List[Finding]:
    """Analyze every ``.py`` under ``paths``; the metrics-label registry is
    built from the first ``metrics.py`` encountered in the scanned set, and
    the span-stage registry from the first ``spans.py``.

    ``jobs``: worker processes for the per-file pass (``None`` = pick from
    the CPU count; ``1`` = in-process).  ``use_cache``: re-use per-file
    results for unchanged sources from ``<root>/.lint-cache.json``
    (requires ``root``).
    """
    from . import lockgraph

    files = list(_iter_py_files(paths))
    metric_labels: Optional[Dict[str, Tuple[str, ...]]] = None
    span_stages: Optional[Tuple[str, ...]] = None
    metrics_py: Optional[str] = None
    for path in files:
        base = os.path.basename(path)
        if base == "metrics.py" and metric_labels is None:
            metrics_py = path
            with open(path, "r", encoding="utf-8") as fh:
                metric_labels = collect_metric_labels(ast.parse(fh.read()))
        elif base == "spans.py" and span_stages is None:
            with open(path, "r", encoding="utf-8") as fh:
                span_stages = collect_span_stages(ast.parse(fh.read()))
        if metric_labels is not None and span_stages is not None:
            break

    def rel(path: str) -> str:
        out = os.path.relpath(path, root) if root else path
        return out.replace(os.sep, "/")

    # Registry changes invalidate per-file results even when the file
    # itself is byte-identical (metrics-labels / span-names look them up).
    context_fp = repr((sorted((metric_labels or {}).items()), span_stages))

    cache_path = (
        os.path.join(root, CACHE_BASENAME) if (root and use_cache) else None
    )
    cached = _load_cache(cache_path) if cache_path else {}

    sources: Dict[str, str] = {}
    keys: Dict[str, str] = {}
    analyses: Dict[str, FileAnalysis] = {}
    misses: List[str] = []
    for path in files:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        r = rel(path)
        sources[r] = source
        keys[r] = _entry_key(source, context_fp)
        entry = cached.get(r)
        if entry is not None and entry.get("key") == keys[r]:
            try:
                analyses[r] = _deserialize_analysis(r, entry["data"])
                continue
            except (KeyError, TypeError, ValueError):
                pass
        misses.append(r)

    if jobs is None:
        jobs = min(8, os.cpu_count() or 1)
    if jobs > 1 and len(misses) >= 4:
        import multiprocessing

        try:
            ctx = multiprocessing.get_context("fork")
        except ValueError:  # platform without fork: spawn re-imports fine
            ctx = multiprocessing.get_context("spawn")
        work = [
            (r, sources[r], metric_labels, span_stages) for r in misses
        ]
        try:
            with ctx.Pool(min(jobs, len(work))) as pool:
                for r, data in pool.map(_pool_worker, work):
                    analyses[r] = _deserialize_analysis(r, data)
            misses = []
        except Exception:
            pass  # pool unavailable (sandbox, recursion): fall through serial
    for r in misses:
        analyses[r] = _analyze_module(
            sources[r], r, metric_labels, span_stages
        )

    if cache_path:
        _store_cache(
            cache_path,
            {
                r: {"key": keys[r], "data": _serialize_analysis(fa)}
                for r, fa in analyses.items()
            },
        )

    findings: List[Finding] = []
    for r in sorted(analyses):
        findings.extend(analyses[r].findings)

    # -- repo-level rules over the merged set ---------------------------------

    # Lock-order: cycles in the package-wide acquisition graph.
    all_edges = [e for fa in analyses.values() for e in fa.locks.edges]
    for path_, line, message in lockgraph.lock_order_messages(
        lockgraph.find_lock_cycles(all_edges)
    ):
        findings.append(Finding(RULE_LOCK_ORDER, path_, line, 0, message))

    # Stale GUARDED_FIELDS annotations, anchored at the registry entry.
    checker_rel = next(
        (
            r
            for r in sorted(analyses)
            if r.endswith("analysis/checker.py")
        ),
        None,
    )
    if checker_rel is not None:
        checker_src = sources[checker_rel].splitlines()
        for attr, _lock, message in lockgraph.stale_annotations(
            [fa.locks for fa in analyses.values()], GUARDED_FIELDS
        ):
            line = next(
                (
                    i
                    for i, text in enumerate(checker_src, start=1)
                    if f'"{attr}"' in text and "GUARDED" not in text
                ),
                1,
            )
            findings.append(
                Finding(RULE_GUARD_INFERENCE, checker_rel, line, 0, message)
            )

    # Repo-level metrics-doc rule: runs whenever the scanned set contains
    # the package metrics.py and the repo carries docs/observability.md
    # (the series inventory of record).
    if metrics_py is not None:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(metrics_py)))
        doc = os.path.join(repo, "docs", "observability.md")
        if os.path.exists(doc):
            with open(metrics_py, "r", encoding="utf-8") as fh:
                metric_names = collect_metric_names(ast.parse(fh.read()))
            with open(doc, "r", encoding="utf-8") as fh:
                doc_text = fh.read()
            findings.extend(
                check_metrics_doc(
                    metric_names, rel(metrics_py), doc_text, rel(doc)
                )
            )

    # -- suppression application + hygiene ------------------------------------

    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for path_ in sorted(set(by_path) | set(analyses)):
        group = by_path.get(path_, [])
        fa = analyses.get(path_)
        suppressions = fa.suppressions if fa is not None else {}
        kept, used = _apply_suppressions(group, suppressions)
        out.extend(kept)
        if fa is not None:
            out.extend(
                _unused_suppression_findings(path_, suppressions, used)
            )
    return sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule))


# -- baseline -----------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    payload = {
        "comment": (
            "mysticeti-lint baseline: pre-existing findings tolerated at "
            "CI-gate time. Regenerate with `python -m mysticeti_tpu.analysis "
            "--baseline-regen` (or tools/lint.py --baseline-regen) after "
            "deliberate changes; prefer fixing or inline-ignoring over "
            "baselining."
        ),
        "findings": dict(sorted(counts.items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")


def new_findings(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> List[Finding]:
    """Findings beyond the baselined count per fingerprint (zero-new gate)."""
    budget = dict(baseline)
    out: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            out.append(f)
    return out
