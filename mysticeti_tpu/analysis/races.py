"""Await-atomicity race rule (``await-atomicity``).

The static twin of the interleave races hardened by hand in PRs 4/5: a
coroutine that reads ``self._x``, suspends at an ``await``, and then
writes ``self._x`` has published a stale snapshot — any other coroutine
scheduled in the gap can update the field and have its write silently
discarded when the first coroutine resumes.  On the deterministic loop
the interleaving is seed-stable, which makes these races *reproducible*
but no less wrong: a different seed (or a production loop) picks a
different winner.

What fires
----------
A read of a ``self.<field>`` attribute followed — across at least one
suspension point (``await``, ``async for``, or entering an
``async with``) — by a write to the same field, inside one ``async def``,
when no single acquisition of a ``self.<lock>`` block covers both the
read and the write.  ``self._x += 1`` after an earlier read counts as the
write half (it is itself a read-modify-write).

What does not fire
------------------
- Read and write inside the *same* ``with self._lock:`` /
  ``async with self._lock:`` block (the lock is held across the
  suspension, so no peer can interleave).  Two separate acquisitions of
  the same lock do **not** protect — that is the classic check-then-act.
- Functions (or whole classes) annotated ``# lint: single-owner[...]``
  on the ``def``/``class`` line or the line above: the repo's core-task
  discipline (core_task.py) serializes all consensus mutations through
  one dispatcher, so its handlers never interleave with each other even
  though they await.
- Writes in ``__init__`` / ``__aenter__`` (construction is single
  threaded by contract).
- Fields the function *only* writes after the await (no prior read: a
  blind publish is last-writer-wins by design, not a lost update).

The traversal is linear in source order — branches are treated as
sequential, which errs toward reporting.  Deliberate exceptions take a
``# lint: ignore[await-atomicity]`` with a justification, same as every
other rule in this package.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

RULE_AWAIT_ATOMICITY = "await-atomicity"

_SINGLE_OWNER_RE = re.compile(r"#\s*lint:\s*single-owner(?:\[([a-z0-9_\-]+)\])?")

# Constructors whose instance attributes we treat as locks when looking
# for protecting ``with self.<lock>:`` blocks.  Mirrors
# checker._collect_class_locks but also accepts asyncio primitives: an
# ``async with self._mutex:`` held across the await is exactly the
# protection this rule credits.
_LOCK_CONSTRUCTORS = frozenset(
    {
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "asyncio.Lock",
        "asyncio.Condition",
    }
)

_CONSTRUCTOR_METHODS = frozenset({"__init__", "__aenter__", "__post_init__"})


@dataclass(frozen=True)
class RaceFinding:
    line: int
    col: int
    message: str


def single_owner_lines(source: str) -> Set[int]:
    """Lines carrying a ``# lint: single-owner`` annotation."""
    from .checker import comment_lines

    out: Set[int] = set()
    for i, line in comment_lines(source).items():
        if _SINGLE_OWNER_RE.search(line):
            out.add(i)
    return out


def _is_annotated(node: ast.AST, owner_lines: Set[int]) -> bool:
    line = getattr(node, "lineno", 0)
    return line in owner_lines or (line - 1) in owner_lines


def _class_locks(cls: Optional[ast.ClassDef], aliases: Dict[str, str]) -> Set[str]:
    """Attribute names assigned a lock constructor anywhere in the class."""
    if cls is None:
        return set()
    from .checker import _dotted  # local import: avoid cycle at module load

    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        ctor = _dotted(node.value.func, aliases)
        if ctor not in _LOCK_CONSTRUCTORS:
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                locks.add(target.attr)
    return locks


class _CoroutineWalk:
    """Source-order walk of one coroutine body.

    Tracks, per ``self.<field>``:

    - the earliest read: (await_count, lock-block ids held at the read)
    - every write after a later suspension point

    Suspension points bump ``await_count``.  Lock blocks are identified by
    the ``with`` node id so that two acquisitions of the same lock are
    distinct — only a shared id (one contiguous critical section) counts
    as protection.
    """

    def __init__(self, locks: Set[str]) -> None:
        self.locks = locks
        self.await_count = 0
        self.lock_stack: List[int] = []  # id(with-node) per held lock block
        # field -> (await_count at first read, frozenset of lock block ids)
        self.reads: Dict[str, Tuple[int, frozenset]] = {}
        self.findings: List[Tuple[ast.AST, str]] = []
        self._reported: Set[str] = set()

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _self_field(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _lock_attr(self, item: ast.withitem) -> bool:
        field = self._self_field(item.context_expr)
        return field is not None and field in self.locks

    def _note_read(self, field: str) -> None:
        # Keep the *latest* read: a re-read after further suspensions means
        # the value in hand is no longer stale relative to those awaits
        # (e.g. a ``while`` condition re-checked after its body's awaits).
        self.reads[field] = (self.await_count, frozenset(self.lock_stack))

    def _note_write(self, node: ast.AST, field: str) -> None:
        prior = self.reads.get(field)
        if prior is None or field in self._reported:
            return
        read_count, read_locks = prior
        if self.await_count <= read_count:
            return  # no suspension between read and write
        if read_locks & frozenset(self.lock_stack):
            return  # one critical section covers both sides
        self._reported.add(field)
        self.findings.append((node, field))

    # -- traversal -------------------------------------------------------

    def walk(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested definitions get their own pass
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
            if isinstance(stmt, ast.AsyncWith):
                self.await_count += 1  # __aenter__ suspends
            pushed = 0
            for item in stmt.items:
                if self._lock_attr(item):
                    self.lock_stack.append(id(stmt))
                    pushed += 1
            self.walk(stmt.body)
            for _ in range(pushed):
                self.lock_stack.pop()
            return
        if isinstance(stmt, ast.AsyncFor):
            self._scan_expr(stmt.iter)
            self.await_count += 1
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.While)):
            # A loop body may run again after its own awaits: re-walk once
            # so a read late in the body pairs with a write early in it.
            before = self.await_count
            if isinstance(stmt, ast.While):
                self._scan_expr(stmt.test)
            else:
                self._scan_expr(stmt.iter)
            self.walk(stmt.body)
            if self.await_count > before:
                self.walk(stmt.body)
            if isinstance(stmt, ast.While):
                # The condition is re-evaluated after the body's awaits;
                # its *last* read happens at the current count, so a
                # ``while self._full(): await ...`` guard followed by an
                # un-suspended write is the correct semaphore shape, not a
                # stale check-then-act.
                self._scan_expr(stmt.test)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            for handler in stmt.handlers:
                self.walk(handler.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assignment(stmt)
            return
        self._scan_expr_reads(stmt)

    def _assignment(self, stmt: ast.stmt) -> None:
        value = getattr(stmt, "value", None)
        if value is not None:
            self._scan_expr(value)
        targets = (
            stmt.targets
            if isinstance(stmt, ast.Assign)
            else [stmt.target]  # AnnAssign / AugAssign
        )
        for target in targets:
            field = self._self_field(target)
            if field is None:
                # Tuple targets, subscripts of fields, etc: reads for the
                # base object, not a whole-field overwrite.
                self._scan_expr_reads(target)
                continue
            # An AugAssign re-reads at write time in one un-suspended step,
            # so it neither loses an update itself nor leaves a stale
            # snapshot behind for a later write to publish: it counts as a
            # write (pairing with an earlier *bound* read — the stale-guard
            # shape) but does not register a read.
            self._note_write(target, field)

    def _scan_expr(self, expr: ast.AST) -> None:
        """Suspension points + field reads in a *persisting* context.

        Only reads whose value can outlive the statement register as the
        stale half of a race: assignment right-hand sides (the value is
        bound) and branch conditions (the decision is taken).  A field read
        as a call argument or receiver (``metrics.set(self.n)``,
        ``self._q.get()``) is consumed in place — it cannot publish a stale
        snapshot later, so it only counts for its awaits.
        """
        for node in ast.walk(expr):
            if isinstance(node, ast.Await):
                self.await_count += 1
                continue
            field = self._self_field(node)
            if field is not None and isinstance(node.ctx, ast.Load):
                self._note_read(field)

    def _scan_expr_reads(self, node: ast.AST) -> None:
        """Count suspension points only (non-persisting read context)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Await):
                self.await_count += 1


def check_await_atomicity(
    tree: ast.AST, aliases: Dict[str, str], source: str
) -> List[RaceFinding]:
    owner_lines = single_owner_lines(source)
    findings: List[RaceFinding] = []

    def visit(node: ast.AST, cls: Optional[ast.ClassDef]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                if _is_annotated(child, owner_lines):
                    continue  # whole class is single-owner
                visit(child, child)
                continue
            if isinstance(child, ast.AsyncFunctionDef):
                if (
                    child.name not in _CONSTRUCTOR_METHODS
                    and not _is_annotated(child, owner_lines)
                ):
                    walk = _CoroutineWalk(_class_locks(cls, aliases))
                    walk.walk(child.body)
                    for site, field in walk.findings:
                        findings.append(
                            RaceFinding(
                                line=site.lineno,
                                col=site.col_offset,
                                message=(
                                    f"self.{field} is read before an await and "
                                    f"written after it in '{child.name}' with no "
                                    "lock held across the suspension — a peer "
                                    "coroutine scheduled in the gap loses its "
                                    "update; hold one critical section across "
                                    "both sides, or annotate the owner with "
                                    "'# lint: single-owner[...]'"
                                ),
                            )
                        )
            visit(child, cls)

    visit(tree, None)
    findings.sort(key=lambda f: (f.line, f.col))
    return findings
