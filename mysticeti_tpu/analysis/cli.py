"""Command-line front end: ``python -m mysticeti_tpu.analysis`` (and the
``tools/lint.py`` alias).

Exit codes: 0 = clean (no new findings beyond the baseline), 1 = new
findings, 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from .checker import (
    RULES,
    analyze_paths,
    load_baseline,
    new_findings,
    write_baseline,
)

_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PACKAGE_ROOT)
DEFAULT_BASELINE = os.path.join(
    _PACKAGE_ROOT, "analysis", "baseline.json"
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m mysticeti_tpu.analysis",
        description=(
            "mysticeti-lint: AST invariant checker (async-safety, lock "
            "discipline, JAX kernel purity, wall-clock use, metrics labels)"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to analyze (default: the mysticeti_tpu package)",
    )
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file of tolerated findings (default: analysis/baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding; ignore the baseline",
    )
    parser.add_argument(
        "--baseline-regen",
        action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON (alias for --format json)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default=None,
        help="output format (sarif = SARIF 2.1.0 for CI/editor ingestion)",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help=(
            "report only findings in files changed vs git HEAD (staged, "
            "unstaged, or untracked); the analysis itself still runs "
            "repo-wide so cross-file rules stay sound"
        ),
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=None,
        metavar="N",
        help="worker processes for the per-file pass (default: auto; 1 = serial)",
    )
    parser.add_argument(
        "--no-cache",
        action="store_true",
        help="ignore and do not write the per-file result cache (.lint-cache.json)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule ids and exit"
    )
    return parser


def _git_changed_files(repo: str) -> Optional[set]:
    """Repo-relative paths changed vs HEAD (staged+unstaged+untracked);
    None when git is unavailable (caller falls back to unfiltered)."""
    import subprocess

    changed: set = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd, cwd=repo, capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        changed.update(
            line.strip() for line in proc.stdout.splitlines() if line.strip()
        )
    return changed


def _to_sarif(findings) -> dict:
    return {
        "version": "2.1.0",
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "mysticeti-lint",
                        "informationUri": "docs/static-analysis.md",
                        "rules": [{"id": rule} for rule in RULES],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {
                                        "startLine": max(1, f.line),
                                        "startColumn": f.col + 1,
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in RULES:
            print(rule)
        return 0

    fmt = args.format or ("json" if args.as_json else "text")

    paths: List[str] = list(args.paths) or [_PACKAGE_ROOT]
    for path in paths:
        if not os.path.exists(path):
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2

    findings = analyze_paths(
        paths,
        root=_REPO_ROOT,
        jobs=args.jobs,
        use_cache=not args.no_cache,
    )

    if args.baseline_regen:
        write_baseline(args.baseline, findings)
        print(
            f"baseline regenerated with {len(findings)} finding(s) -> "
            f"{os.path.relpath(args.baseline, _REPO_ROOT)}"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    fresh = new_findings(findings, baseline)

    if args.changed:
        changed = _git_changed_files(_REPO_ROOT)
        if changed is None:
            print(
                "warning: --changed requested but git diff failed; "
                "reporting all findings",
                file=sys.stderr,
            )
        else:
            fresh = [f for f in fresh if f.path in changed]

    if fmt == "sarif":
        print(json.dumps(_to_sarif(fresh), indent=2))
    elif fmt == "json":
        print(
            json.dumps(
                [
                    {
                        "rule": f.rule,
                        "path": f.path,
                        "line": f.line,
                        "col": f.col,
                        "message": f.message,
                    }
                    for f in fresh
                ],
                indent=2,
            )
        )
    else:
        for f in fresh:
            print(f.render())
        baselined = len(findings) - len(fresh)
        tail = f" ({baselined} baselined)" if baselined else ""
        print(
            f"mysticeti-lint: {len(fresh)} new finding(s) over "
            f"{len(paths)} path(s){tail}"
        )
    return 1 if fresh else 0
