"""mysticeti-lint: AST-based invariant checker for this codebase's failure modes.

The hardest correctness rules in this repository were, until this package,
encoded only in comments — "the backend label must be captured in the same
thread as the dispatch" (block_validator.py), "EMA read-modify-writes happen
from executor threads; serialize them", "the device dispatch runs in a worker
thread so the event loop never blocks".  This package mechanizes them as
stdlib-``ast`` rules, runnable as ``python -m mysticeti_tpu.analysis``:

* ``async-blocking``   — blocking call (``time.sleep``, sync subprocess/socket
  I/O, a direct ``verify_signatures`` dispatch) inside an ``async def`` body
  without ``run_in_executor``.
* ``task-orphan``      — ``asyncio.ensure_future``/``create_task`` whose handle
  is never awaited and never given an exception-logging done-callback (the
  swallowed-exception pattern); ``utils.tasks.spawn_logged`` is the compliant
  spawner.
* ``lock-discipline``  — ``await`` inside a ``threading.Lock`` ``with`` block
  (deadlocks the event loop), and designated shared EMA/counter fields mutated
  outside their designated lock.
* ``jit-purity``       — host-side impurities (``.item()``, ``np.*`` calls,
  ``print``, ``jax.debug.print``, wall-clock reads) inside ``@jax.jit``-
  compiled or pallas kernel functions under ``ops/`` and ``parallel/``.
* ``wall-clock``       — ``time.time()`` used to measure an interval where
  ``time.monotonic()`` is required (wall clock steps under NTP).
* ``metrics-labels``   — every ``.labels(...)`` call site must match the
  arity/names declared for that series in ``metrics.py``.
* ``span-names``       — every literal stage passed to the span-tracer call
  surface (``span``/``begin_span``/``end_span``/``record_span``) must come
  from the central registry ``spans.STAGES`` (a typo'd stage silently never
  matches its begin/end and vanishes from traces).
* ``metrics-doc``      — every series registered in ``metrics.py`` must appear
  in ``docs/observability.md`` (the series inventory of record), and every
  ``mysticeti_*`` series the doc names must be registered — the inventory
  cannot drift from the doc in either direction.

Exit status: 0 = no new findings, 1 = new findings (or bad usage: 2).
Deliberate exceptions carry an inline ``# lint: ignore[rule]`` suppression;
legacy debt lives in ``analysis/baseline.json`` (regenerate with
``python -m mysticeti_tpu.analysis --baseline-regen`` or
``tools/lint.py --baseline-regen``).  See ``docs/static-analysis.md``.
"""
from .checker import (
    Finding,
    RULES,
    analyze_file,
    analyze_paths,
    analyze_source,
    check_metrics_doc,
    collect_metric_names,
    load_baseline,
    new_findings,
    write_baseline,
)
from .cli import main

__all__ = [
    "Finding",
    "RULES",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "check_metrics_doc",
    "collect_metric_names",
    "load_baseline",
    "main",
    "new_findings",
    "write_baseline",
]
