"""The ``sim-taint`` rule: nondeterminism dataflow into sim-visible state.

The determinism contract of this codebase is that a seeded virtual-time run
(:mod:`mysticeti_tpu.runtime.simulated`) is byte-identical across re-runs.
Twice now a plane shipped with a leak the per-call-site ``wall-clock`` rule
could not see, because the *read* was innocent and the *use* was elsewhere:

* **PR 11**: the ingress admission controller's ``wal_backlog`` signal read
  ``wal_writer.pending()`` — the live progress of a real drain *thread* —
  and a virtual-time sim's shed schedule absorbed host thread timing.
* **PR 12**: the batched verifier folded a wall-clock dispatch measurement
  into ``self._dispatch_ema_s``, and ``_effective_delay_s`` armed a
  *virtual-time* flush timer from it — the sim's whole commit trajectory
  followed host load.

Both are **taint** bugs: a nondeterminism *source* (wall-clock read, global
RNG, thread-progress observation) flowing into a sim-visible *sink* (a
branch decision, a timer delay, a canonical digest).  This module tracks
that flow intra-module, flow-insensitively, through three channels:

* **locals** within a function (``started = time.monotonic()``),
* **self fields** within a class, to a fixed point across methods
  (``self._ema = _update(self._ema, wall, ...)`` taints every later read),
* **dict keys** module-wide (``signals["wal_backlog"] = ...`` taints
  ``signals.get("wal_backlog")`` in another class of the same module —
  exactly the shape of the PR 11 bug).

Reads executed only in real-time mode are *clean*: a source lexically under
``if not runtime.is_simulated():`` (or the ``else`` of ``if
is_simulated():``, or after an ``if is_simulated(): return`` early exit, or
guarded by a local assigned ``not is_simulated()``) never runs inside the
virtual-time loop, so it cannot leak into a sim.  That gating idiom is the
sanctioned escape hatch — the rule exists to force nondeterministic reads
through it.

Like every rule in this package the detector is deliberately syntactic and
idiom-scoped: precision over generality.  Calls propagate taint from
arguments to result (``_update_ema(ema, wall_delta)`` is tainted), but only
three sink shapes fire: ``if``/``while`` decisions, virtual-timer delays
(``call_later``/``call_at``/``sleep``/``wait_for``), and digest feeds.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULE_SIM_TAINT = "sim-taint"

# -- taint sources ------------------------------------------------------------

# Host clock reads: real time observed from inside what may be a virtual-time
# run.  (runtime.now()/timestamp_utc() are the clean equivalents — they read
# the loop clock under simulation.)
WALL_CLOCK_SOURCES = frozenset({
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
})

# Process-global / OS randomness: not derived from the loop's seeded RNG, so
# two same-seed runs draw differently.  Seeded instances (``self._rng.random()``,
# ``loop.rng.choice(...)``) resolve to a different dotted head and stay clean.
UNSEEDED_RANDOM_SOURCES = frozenset({
    "random.random", "random.uniform", "random.randint", "random.randrange",
    "random.choice", "random.choices", "random.sample", "random.shuffle",
    "random.gauss", "random.expovariate", "random.getrandbits",
    "random.betavariate", "random.normalvariate",
    "os.urandom",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.randbelow", "secrets.choice",
    "uuid.uuid1", "uuid.uuid4",
})

# Observations of real-thread progress: how far a drain/worker thread has
# gotten is wall-clock state no matter how it is read.  ``pending()`` is the
# WalWriter in-flight census (the PR 11 source); ``as_completed`` yields in
# completion order; ``Thread.is_alive`` is the thread's own progress bit.
THREAD_PROGRESS_METHODS = frozenset({"pending", "is_alive"})
THREAD_PROGRESS_CALLS = frozenset({
    "concurrent.futures.as_completed", "futures.as_completed",
})

_SOURCE_KIND = {
    **{name: "wall-clock" for name in WALL_CLOCK_SOURCES},
    **{name: "unseeded-random" for name in UNSEEDED_RANDOM_SOURCES},
    **{name: "thread-progress" for name in THREAD_PROGRESS_CALLS},
}

# -- sinks --------------------------------------------------------------------

# Arming a timer: under the DeterministicLoop the delay IS virtual time, so a
# tainted delay reshapes the whole event schedule.
TIMER_SINK_TAILS = frozenset({"call_later", "call_at", "sleep", "wait_for"})

# Feeding a canonical digest: sims assert byte-identity on these.
DIGEST_SINK_TAILS = frozenset({
    "sha256", "sha512", "sha3_256", "blake2b", "blake2s", "md5",
})


@dataclass(frozen=True)
class Taint:
    """Provenance of one nondeterminism source reaching a value."""

    kind: str       # wall-clock | unseeded-random | thread-progress
    source: str     # dotted call, e.g. "time.monotonic" or ".pending()"
    line: int


@dataclass(frozen=True)
class TaintFinding:
    line: int
    col: int
    message: str
    # The source's line: an inline suppression at the *cause* (one comment
    # at the nondeterministic read) silences every downstream sink finding,
    # instead of one comment per sink.
    source_line: int = 0


def _dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def _is_simulated_call(node: ast.AST) -> bool:
    """``is_simulated()`` / ``runtime.is_simulated()`` / ``self._sim()``-free:
    any call whose tail name is ``is_simulated``."""
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "is_simulated"
    if isinstance(func, ast.Attribute):
        return func.attr == "is_simulated"
    return False


class _GateClassifier:
    """Classifies condition expressions as real-only / sim-only gates.

    ``real`` — the guarded body only executes outside the simulator
    (``not is_simulated()``, a local assigned from it, ``x and real_flag``).
    ``sim`` — the body only executes *inside* the simulator.
    ``None`` — no verdict.
    """

    def __init__(self) -> None:
        self.real_flags: Set[str] = set()   # locals holding not is_simulated()
        self.sim_flags: Set[str] = set()    # locals holding is_simulated()

    def note_assign(self, node: ast.Assign) -> None:
        value = node.value
        verdict = self.classify(value)
        if verdict is None:
            return
        for target in node.targets:
            if isinstance(target, ast.Name):
                if verdict == "real":
                    self.real_flags.add(target.id)
                    self.sim_flags.discard(target.id)
                else:
                    self.sim_flags.add(target.id)
                    self.real_flags.discard(target.id)

    def classify(self, test: ast.AST) -> Optional[str]:
        if _is_simulated_call(test):
            return "sim"
        if isinstance(test, ast.Name):
            if test.id in self.real_flags:
                return "real"
            if test.id in self.sim_flags:
                return "sim"
            return None
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = self.classify(test.operand)
            if inner == "sim":
                return "real"
            if inner == "real":
                return "sim"
            return None
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            # ``a and not is_simulated()``: body runs only when every
            # conjunct holds, so one real-only conjunct gates the body.
            verdicts = [self.classify(v) for v in test.values]
            if "real" in verdicts:
                return "real"
            if "sim" in verdicts:
                return "sim"
        return None


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    if not stmts:
        return False
    last = stmts[-1]
    return isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break))


class _FunctionFlow(ast.NodeVisitor):
    """One pass over a function body: collects taints and sink hits.

    ``field_taints`` (per class) and ``key_taints`` (per module) are shared
    mutable dicts — the module driver iterates functions to a fixed point so
    cross-method field flow and cross-class dict-key flow both resolve.
    """

    def __init__(
        self,
        aliases: Dict[str, str],
        gates: _GateClassifier,
        field_taints: Dict[str, Taint],
        key_taints: Dict[str, Taint],
        findings: List[TaintFinding],
        emitted: Set[Tuple[int, int, str]],
        func_name: Optional[str] = None,
    ) -> None:
        self.aliases = aliases
        self.gates = gates
        self.field_taints = field_taints
        self.key_taints = key_taints
        self.findings = findings
        self.emitted = emitted
        self.func_name = func_name
        self.local_taints: Dict[str, Taint] = {}
        self._real_only = 0  # depth of real-only gating
        self.changed = False

    # -- taint queries --

    def _source_of_call(self, node: ast.Call) -> Optional[Taint]:
        dotted = _dotted(node.func, self.aliases)
        if dotted in _SOURCE_KIND:
            return Taint(_SOURCE_KIND[dotted], dotted, node.lineno)
        func = node.func
        if isinstance(func, ast.Attribute) and \
                func.attr in THREAD_PROGRESS_METHODS:
            return Taint("thread-progress", f".{func.attr}()", node.lineno)
        return None

    def _taint_of(self, node: ast.AST) -> Optional[Taint]:
        """Taint provenance of an expression, or None if clean."""
        if isinstance(node, ast.Call):
            src = self._source_of_call(node)
            if src is not None:
                return None if self._real_only else src
            if _is_simulated_call(node):
                return None
            # Calls propagate taint from arguments: the EMA-update helper,
            # bool()/min()/max() wrappers, f(x) of a tainted x.
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                t = self._taint_of(arg)
                if t is not None:
                    return t
            # ``self.method()`` where the method returns a tainted value
            # (resolved through the class field/method-taint namespace), or
            # a method call on a tainted object observing tainted state.
            if isinstance(node.func, ast.Attribute):
                return self._taint_of(node.func)
            return None
        if isinstance(node, ast.Name):
            return self.local_taints.get(node.id)
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return self.field_taints.get(node.attr)
            return self._taint_of(node.value)
        if isinstance(node, ast.Subscript):
            key = _const_key(node.slice)
            if key is not None and key in self.key_taints:
                return self.key_taints[key]
            return self._taint_of(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self._taint_of(node.left) or self._taint_of(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._taint_of(node.operand)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                t = self._taint_of(v)
                if t is not None:
                    return t
            return None
        if isinstance(node, ast.Compare):
            for v in [node.left] + list(node.comparators):
                t = self._taint_of(v)
                if t is not None:
                    return t
            return None
        if isinstance(node, ast.IfExp):
            return (
                self._taint_of(node.body)
                or self._taint_of(node.orelse)
                or self._taint_of(node.test)
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                t = self._taint_of(e)
                if t is not None:
                    return t
            return None
        if isinstance(node, ast.Dict):
            for v in node.values:
                if v is None:
                    continue
                t = self._taint_of(v)
                if t is not None:
                    return t
            return None
        if isinstance(node, ast.Starred):
            return self._taint_of(node.value)
        if isinstance(node, ast.Await):
            return self._taint_of(node.value)
        if isinstance(node, ast.NamedExpr):
            return self._taint_of(node.value)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                t = self._taint_of(v)
                if t is not None:
                    return t
            return None
        if isinstance(node, ast.FormattedValue):
            return self._taint_of(node.value)
        return None

    # ``x.get("k")`` reads a dict key.
    def _get_call_key_taint(self, node: ast.Call) -> Optional[Taint]:
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "get"
            and node.args
        ):
            key = _const_key(node.args[0])
            if key is not None:
                return self.key_taints.get(key)
        return None

    # -- taint recording --

    def _record_local(self, name: str, taint: Optional[Taint]) -> None:
        if taint is None:
            return
        if self.local_taints.get(name) is None:
            self.local_taints[name] = taint
            self.changed = True

    def _record_field(self, attr: str, taint: Optional[Taint]) -> None:
        if taint is None or self._real_only:
            return
        if self.field_taints.get(attr) is None:
            self.field_taints[attr] = taint
            self.changed = True

    def _record_key(self, key: str, taint: Optional[Taint]) -> None:
        if taint is None or self._real_only:
            return
        if self.key_taints.get(key) is None:
            self.key_taints[key] = taint
            self.changed = True

    # -- emit --

    def _emit(self, node: ast.AST, taint: Taint, sink: str) -> None:
        if self._real_only:
            return
        key = (node.lineno, node.col_offset, sink)
        if key in self.emitted:
            return
        self.emitted.add(key)
        self.findings.append(
            TaintFinding(
                node.lineno,
                node.col_offset,
                f"nondeterministic value ({taint.kind}: {taint.source}, "
                f"line {taint.line}) reaches {sink} — a seeded sim absorbs "
                "host state here; gate the source with "
                "runtime.is_simulated() or derive it from the loop clock",
                source_line=taint.line,
            )
        )

    # -- statements --

    def visit_Assign(self, node: ast.Assign) -> None:
        self.gates.note_assign(node)
        taint = self._taint_of(node.value)
        if taint is None and isinstance(node.value, ast.Call):
            taint = self._get_call_key_taint(node.value)
        for target in node.targets:
            self._assign_target(target, taint)
        self.generic_visit(node)

    def _assign_target(self, target: ast.AST, taint: Optional[Taint]) -> None:
        if isinstance(target, ast.Name):
            if taint is not None:
                self._record_local(target.id, taint)
            else:
                # Re-assignment with a clean value does NOT clear existing
                # taint (flow-insensitive join), matching the fixed point.
                pass
        elif isinstance(target, ast.Attribute):
            if isinstance(target.value, ast.Name) and target.value.id == "self":
                self._record_field(target.attr, taint)
        elif isinstance(target, ast.Subscript):
            key = _const_key(target.slice)
            if key is not None:
                self._record_key(key, taint)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_target(e, taint)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        taint = self._taint_of(node.value)
        self._assign_target(node.target, taint)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._assign_target(node.target, self._taint_of(node.value))
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        # Return-value flow: a method returning a tainted value taints every
        # ``self.<method>()`` call site — the PR 12 shape reached its timer
        # through ``_effective_delay_s()`` returning the wall-fed EMA.  The
        # method name shares the class field-taint namespace (attribute
        # reads and bound-method reads resolve identically there).
        if node.value is not None and self.func_name is not None:
            self._record_field(self.func_name, self._taint_of(node.value))
        self.generic_visit(node)

    # -- gating / decisions --

    def _check_decision(self, test: ast.AST, node: ast.AST) -> None:
        taint = self._taint_of(test)
        if taint is None and isinstance(test, ast.Call):
            taint = self._get_call_key_taint(test)
        if taint is None:
            # dig for .get("k") reads nested in bool ops / comparisons
            for sub in ast.walk(test):
                if isinstance(sub, ast.Call):
                    taint = self._get_call_key_taint(sub)
                    if taint is not None:
                        break
        if taint is not None:
            self._emit(
                node, taint,
                "a branch decision (sim-visible control flow)",
            )

    def visit_If(self, node: ast.If) -> None:
        verdict = self.gates.classify(node.test)
        if verdict is None:
            self._check_decision(node.test, node)
        self.visit(node.test)
        if verdict == "real":
            self._real_only += 1
            for stmt in node.body:
                self.visit(stmt)
            self._real_only -= 1
            for stmt in node.orelse:
                self.visit(stmt)
        elif verdict == "sim":
            for stmt in node.body:
                self.visit(stmt)
            self._real_only += 1
            for stmt in node.orelse:
                self.visit(stmt)
            self._real_only -= 1
        else:
            for stmt in node.body:
                self.visit(stmt)
            for stmt in node.orelse:
                self.visit(stmt)

    def visit_While(self, node: ast.While) -> None:
        if self.gates.classify(node.test) is None:
            self._check_decision(node.test, node)
        self.generic_visit(node)

    def _visit_gated_body(self, stmts: Sequence[ast.stmt]) -> None:
        """Visit a statement list honoring ``if is_simulated(): return``
        early exits: statements after a terminal sim-gate are real-only."""
        gated = 0
        for stmt in stmts:
            if (
                isinstance(stmt, ast.If)
                and not stmt.orelse
                and _terminates(stmt.body)
            ):
                verdict = self.gates.classify(stmt.test)
                if verdict == "sim":
                    # sim-mode exits here: the rest is real-only
                    self.visit(stmt)
                    self._real_only += 1
                    gated += 1
                    continue
            self.visit(stmt)
        self._real_only -= gated

    # -- sinks: calls --

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        tail = None
        if isinstance(func, ast.Attribute):
            tail = func.attr
        elif isinstance(func, ast.Name):
            tail = self.aliases.get(func.id, func.id).rsplit(".", 1)[-1]

        if tail in TIMER_SINK_TAILS:
            delay_args: List[ast.AST] = []
            if tail in {"call_later", "call_at", "sleep"} and node.args:
                delay_args.append(node.args[0])
            if tail == "wait_for":
                if len(node.args) > 1:
                    delay_args.append(node.args[1])
                for kw in node.keywords:
                    if kw.arg == "timeout":
                        delay_args.append(kw.value)
            for arg in delay_args:
                taint = self._taint_of(arg)
                if taint is not None:
                    self._emit(
                        node, taint,
                        f"a virtual-time timer delay ({tail}())",
                    )
        if tail in DIGEST_SINK_TAILS or (tail and "digest" in tail):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                taint = self._taint_of(arg)
                if taint is not None:
                    self._emit(
                        node, taint,
                        f"a canonical digest ({tail}())",
                    )
                    break
        self.generic_visit(node)

    # Nested defs get their own flow pass via the module driver; do not
    # descend so their locals stay separate.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass


def _const_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _functions_of(tree: ast.Module):
    """Yield (function node, enclosing ClassDef or None), outermost first,
    including nested defs (each analyzed with its own local scope)."""
    def walk(node: ast.AST, cls: Optional[ast.ClassDef]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from walk(child, cls)
            else:
                yield from walk(child, cls)
    yield from walk(tree, None)


def check_sim_taint(
    tree: ast.Module, aliases: Dict[str, str]
) -> List[TaintFinding]:
    """Run the sim-taint dataflow over one module to a fixed point."""
    functions = list(_functions_of(tree))
    # Shared propagation state: per-class field taints, module-wide key
    # taints.  Iterate until no new taint or finding appears (bounded: the
    # taint lattice only grows and is finite).
    class_fields: Dict[Optional[ast.ClassDef], Dict[str, Taint]] = {}
    key_taints: Dict[str, Taint] = {}
    findings: List[TaintFinding] = []
    emitted: Set[Tuple[int, int, str]] = set()

    for _ in range(8):  # fixed-point iterations; converges in 2-3 in practice
        changed = False
        for fn, cls in functions:
            gates = _GateClassifier()
            # Seed flag locals from a linear prescan so a gate assigned
            # above its use is recognized regardless of visit order.
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign):
                    gates.note_assign(sub)
            flow = _FunctionFlow(
                aliases,
                gates,
                class_fields.setdefault(cls, {}),
                key_taints,
                findings,
                emitted,
                func_name=fn.name if cls is not None else None,
            )
            # Parameters named like injected clocks stay clean: only
            # in-function sources create taint.
            flow._visit_gated_body(fn.body)
            changed = changed or flow.changed
        if not changed:
            break
    return findings
