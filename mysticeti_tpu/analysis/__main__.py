"""``python -m mysticeti_tpu.analysis`` entry point."""
import sys

from .cli import main

sys.exit(main())
