"""Staged verifier dispatch: pack / device / fetch overlap with bounded depth.

The verifier hot path used to pay its fixed per-dispatch cost end-to-end per
batch: one executor thread packed the batch (host numpy), pushed it to the
device, waited for the kernel, and fetched the verdict bits — all serialized,
so a remote accelerator (~100-300 ms per round-trip over a tunnel,
NODE_BENCH_r05.json) capped the whole node at one batch per RTT regardless of
batch size.  Streaming-verification designs (arXiv 2302.00418's committee
pipelines, the FPGA engine of arXiv 2112.02229) get their throughput from
exactly the opposite shape: the host prepares batch N+1 while the device
computes batch N and batch N-1's results ride back.

This module is the engine for that shape:

* :class:`VerifyPipeline` — a bounded in-flight window over dispatches.  The
  batching collector (``block_validator.BatchedSignatureVerifier``) may open
  a new flush window while prior dispatches are still in flight; the window
  bounds how many, so a flooding peer cannot queue unbounded device work.
  Depth adapts to the measured fixed dispatch cost (the hybrid router's
  ``tpu_dispatch_s``): a co-located chip has little latency to hide (depth
  2), a tunneled one wants more overlap (up to 4).
* :class:`DeferredDispatch` / :class:`CompletedDispatch` — future-like
  handles for backends without a native async queue, so every
  ``SignatureVerifier`` presents the same submit-now/fetch-later surface
  (``verify_signatures_async``) whether the work happens on submit, on a JAX
  async dispatch, or behind a socket.

Stage accounting: ``verify_pipeline_inflight`` / ``verify_pipeline_depth``
gauges and the ``verify_pipeline_stage_seconds{stage=pack|device|fetch}``
histogram (metrics.py), plus per-block ``verify_pack`` / ``verify_device`` /
``verify_fetch`` spans (spans.py) when tracing is on.
"""
from __future__ import annotations

import asyncio
from collections import deque
from typing import Callable, List, Optional, Sequence

STAGE_PACK = "pack"
STAGE_DEVICE = "device"
STAGE_FETCH = "fetch"


class CompletedDispatch:
    """An already-resolved dispatch handle (empty batches, cached results)."""

    __slots__ = ("_out",)

    def __init__(self, out) -> None:
        self._out = out

    def result(self):
        return self._out


class DeferredDispatch:
    """Dispatch handle for a synchronous backend: the work runs at
    ``result()`` time, on the fetch stage's executor thread.  That keeps the
    pipeline semantics uniform — overlap still happens because the bounded
    window admits several fetches into distinct executor threads — without
    pretending a host backend has a device queue."""

    __slots__ = ("_fn", "_args")

    def __init__(self, fn: Callable, *args) -> None:
        self._fn = fn
        self._args = args

    def result(self):
        return self._fn(*self._args)


class VerifyPipeline:
    """Bounded in-flight dispatch window (asyncio, single-loop).

    ``slot()`` is an async context manager held from device submission
    through result fetch; at most :meth:`depth` slots are out at once and
    excess flushes queue on acquisition (backpressure toward the collector,
    and through it the per-connection receive pipelines).

    All state is mutated on the event-loop thread only (the collector
    acquires/releases from coroutines), so no lock is needed — the executor
    threads doing the actual pack/dispatch/fetch never touch it.
    """

    MIN_DEPTH = 2
    MAX_DEPTH = 4
    # Fixed-cost thresholds for the adaptive window: a µs-co-located chip
    # has nothing to hide (MIN), a tunneled chip (~100 ms fixed) wants the
    # full window; in between, one intermediate step.
    MID_FIXED_COST_S = 0.005
    DEEP_FIXED_COST_S = 0.050

    def __init__(
        self,
        depth: Optional[int] = None,
        metrics=None,
        fixed_cost_fn: Optional[Callable[[], float]] = None,
    ) -> None:
        self._fixed_depth = depth
        self._fixed_cost_fn = fixed_cost_fn
        self.metrics = metrics
        self._inflight = 0
        self.max_inflight = 0  # high-water mark (tests/telemetry)
        self._waiters: deque = deque()
        # Host attribution plane: cumulative stage seconds, reduced to
        # dispatch-occupancy fractions (device-busy vs host-pack vs
        # fetch-wait) for mysticeti_verify_occupancy_fraction.
        self._stage_totals = {STAGE_PACK: 0.0, STAGE_DEVICE: 0.0,
                              STAGE_FETCH: 0.0}

    # -- depth policy --

    def depth(self) -> int:
        """Current window size: fixed when configured, else adaptive from
        the measured fixed dispatch cost (2 co-located … 4 tunneled)."""
        if self._fixed_depth is not None:
            return max(1, self._fixed_depth)
        fixed = 0.0
        if self._fixed_cost_fn is not None:
            fixed = self._fixed_cost_fn() or 0.0
        if fixed >= self.DEEP_FIXED_COST_S:
            d = self.MAX_DEPTH
        elif fixed >= self.MID_FIXED_COST_S:
            d = (self.MIN_DEPTH + self.MAX_DEPTH) // 2
        else:
            d = self.MIN_DEPTH
        return d

    @property
    def inflight(self) -> int:
        return self._inflight

    # -- the bounded window --

    def slot(self) -> "_PipelineSlot":
        return _PipelineSlot(self)

    async def _acquire(self) -> None:
        while self._inflight >= self.depth():
            event = asyncio.Event()
            self._waiters.append(event)
            await event.wait()
        self._inflight += 1
        if self._inflight > self.max_inflight:
            self.max_inflight = self._inflight
        if self.metrics is not None:
            self.metrics.verify_pipeline_inflight.set(self._inflight)
            self.metrics.verify_pipeline_depth.set(self.depth())

    def _release(self) -> None:
        self._inflight -= 1
        if self.metrics is not None:
            self.metrics.verify_pipeline_inflight.set(self._inflight)
        # Wake every waiter; each rechecks against the (possibly adapted)
        # depth.  Waiter counts are small (bounded by flush concurrency).
        while self._waiters:
            self._waiters.popleft().set()

    # -- stage accounting --

    def note_stage(self, stage: str, seconds: float) -> None:
        if stage in self._stage_totals:
            self._stage_totals[stage] += max(0.0, seconds)
        if self.metrics is not None:
            self.metrics.verify_pipeline_stage_seconds.labels(stage).observe(
                seconds
            )
            for phase, fraction in self.occupancy().items():
                self.metrics.mysticeti_verify_occupancy_fraction.labels(
                    phase
                ).set(round(fraction, 6))

    def occupancy(self) -> dict:
        """Where dispatch wall time goes: {pack, device, fetch} fractions of
        the cumulative stage seconds (all zero before the first dispatch)."""
        total = sum(self._stage_totals.values())
        if total <= 0:
            return {stage: 0.0 for stage in self._stage_totals}
        return {
            stage: seconds / total
            for stage, seconds in self._stage_totals.items()
        }


class _PipelineSlot:
    __slots__ = ("_pipeline",)

    def __init__(self, pipeline: VerifyPipeline) -> None:
        self._pipeline = pipeline

    async def __aenter__(self) -> VerifyPipeline:
        await self._pipeline._acquire()
        return self._pipeline

    async def __aexit__(self, exc_type, exc, tb) -> None:
        self._pipeline._release()


__all__ = [
    "VerifyPipeline",
    "CompletedDispatch",
    "DeferredDispatch",
    "STAGE_PACK",
    "STAGE_DEVICE",
    "STAGE_FETCH",
]
