"""mysticeti-tpu: a TPU-native DAG-consensus framework.

A brand-new implementation (not a port) with the capabilities of the Mysticeti
consensus prototype (reference: hrubaanna/mysticeti): statement-block DAG, threshold
clock rounds, wave-based direct/indirect commit rule with multi-leader + pipelining,
fast-path transaction certification, WAL-backed crash recovery, full-mesh validator
networking, deterministic whole-system simulation, prometheus observability, and a
benchmark harness — with the block-verification hot path (batched Blake2b digests +
Ed25519) executed on TPU via JAX (vmap/jit/shard_map, Pallas kernels).

Package layout:
  types / crypto / serde / committee / range_map / threshold_clock  — L1-L2 foundation
  wal / block_store / state                                         — L3 persistence
  block_manager / core / epoch_close                                — L4 engine
  consensus/                                                        — L5 commit rule
  block_handler / commit_observer / block_validator                 — L6 app interface
  syncer / network / net_sync / synchronizer                        — L8 networking
  runtime/ + simulator                                              — L9 determinism
  metrics                                                           — L10 observability
  ops/                      — JAX/TPU kernels (Ed25519, SHA-512, field arithmetic)
  parallel/                 — mesh/sharding for multi-chip batch verification
  models/                   — assembled verification pipelines (the TPU "models")
"""

__version__ = "0.1.0"
