"""Declarative resilience scenario matrix (the Byzantine evaluation flywheel).

One :class:`Scenario` composes the adversary plane (adversary.py), the
benign chaos plane (chaos.py), the storage lifecycle plane (snapshot
catch-up rejoin), a geo-latency WAN profile, and mixed-version soft-tag
skew into a single seeded, reproducible run — the committee-consensus
measurement shape of arXiv 2302.00418 (vary the committee and the
adversary mix, pin per-scenario artifacts) applied to the chaos tier.

Every scenario runs TWICE on the same seed: the attacked run and a clean
twin (same committee, same network profile, same per-node parameters —
only the faults and adversaries removed), so the committed-throughput
ratio compares like with like.  The verdict is a pure function of the two
seeded runs:

* **safety** — zero :class:`~mysticeti_tpu.chaos.SafetyViolation` among
  honest nodes; adversary-attributed divergence is recorded, not fatal;
* **liveness** — honest committed throughput (honest-authored blocks in
  the honest commit prefix) >= ``min_ratio`` x the clean twin's;
* **detection** — every injected behavior is detected on its surface
  (equivocation / invalid-signature / malformed counters) or, for the
  silence-shaped attacks (withhold, lag) whose only honest-side signal is
  absence, accounted in the attack ledger;
* **reproducibility** — the attack schedule, detection ledger, and
  committed sequences are canonical bytes (digests in the verdict), so a
  same-seed re-run is byte-identical.

``mysticeti-tpu scenarios`` runs one scenario or the whole matrix;
``tools/scenario_matrix.py`` pins the matrix verdicts into the
``SCENARIO_rNN.json`` artifact family consumed by ``tools/bench_trend.py``.
"""
from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .adversary import AdversarySpec
from .chaos import (
    ChaosReport,
    CrashFault,
    FaultPlan,
    LinkFault,
    PartitionFault,
    SafetyViolation,
    run_chaos_sim,
)
from .committee import Committee
from .config import Parameters, StorageParameters, SynchronizerParameters
from .reconfig import (
    CHANGE_ADD,
    CHANGE_REMOVE,
    CHANGE_REWEIGHT,
    CommitteeChange,
)
from .tracing import logger

log = logger(__name__)

# WAN profile: three regions, intra-region fast, cross-region an ocean away.
WAN_INTRA_RANGE = (0.005, 0.015)
WAN_INTER_RANGE = (0.080, 0.160)


def wan_latency_ranges(
    regions: List[int],
) -> Dict[Tuple[int, int], Tuple[float, float]]:
    """Per-directed-link latency ranges from a region assignment (node ->
    region index): intra-region links draw from WAN_INTRA_RANGE, cross-
    region from WAN_INTER_RANGE."""
    n = len(regions)
    out: Dict[Tuple[int, int], Tuple[float, float]] = {}
    for a in range(n):
        for b in range(n):
            if a == b:
                continue
            out[(a, b)] = (
                WAN_INTRA_RANGE if regions[a] == regions[b] else WAN_INTER_RANGE
            )
    return out


@dataclass(frozen=True)
class ChurnEvent:
    """One scheduled membership change in a reconfig scenario.

    At ``at_s`` (virtual seconds) a :class:`CommitteeChange` is planted on
    authority ``via``'s block handler; it rides the committed sequence and
    takes effect at the commit-anchored epoch boundary every honest node
    derives from it.  ``follow_delay_s`` later, the harness performs the
    matching topology act: for ADD, :meth:`ChaosSimHarness.join` boots the
    (previously absent) authority, which discovers the new committee by
    snapshot catch-up or replay; for REMOVE, :meth:`ChaosSimHarness.retire`
    cleanly departs the node — the delay lets the change commit first, so a
    departing leader keeps its slots live until the boundary retires them.
    """

    at_s: float
    kind: int  # CHANGE_ADD / CHANGE_REMOVE / CHANGE_REWEIGHT
    authority: int
    stake: int = 0
    via: int = 0
    follow_delay_s: float = 2.0

    def to_dict(self) -> dict:
        return {
            "at_s": self.at_s,
            "kind": {
                CHANGE_ADD: "add",
                CHANGE_REMOVE: "remove",
                CHANGE_REWEIGHT: "reweight",
            }.get(self.kind, str(self.kind)),
            "authority": self.authority,
            "stake": self.stake,
            "via": self.via,
            "follow_delay_s": self.follow_delay_s,
        }


@dataclass(frozen=True)
class Scenario:
    """One declarative matrix entry.  Everything the run needs is here (or
    derived deterministically from it), so ``to_dict`` IS the scenario's
    reproduction recipe."""

    name: str
    description: str
    nodes: int
    duration_s: float
    seed: int = 0
    adversaries: Tuple[AdversarySpec, ...] = ()
    link_faults: Tuple[LinkFault, ...] = ()
    partitions: Tuple[PartitionFault, ...] = ()
    crashes: Tuple[CrashFault, ...] = ()
    # Honest committed throughput must stay >= this fraction of the clean
    # twin's (same seed, faults and adversaries removed).
    min_ratio: float = 0.8
    leader_timeout_s: float = 0.5
    # Geo profile: region index per node (() = uniform sim default).
    regions: Tuple[int, ...] = ()
    # Uniform link profile: one-way latency range for EVERY directed link
    # (None = the sim default 50-100 ms).  The default's ±33% jitter is far
    # above real WAN links; stable-link scenarios pin e.g. (0.08, 0.10) so
    # the measured Byzantine throughput tax is the protocol's, not the
    # jitter lottery's.  Ignored when ``regions`` is set.
    latency: Optional[Tuple[float, float]] = None
    # Storage lifecycle: arm segmented WAL + checkpoints + snapshot
    # catch-up with sim-scaled knobs (the churn-rejoin scenarios).
    snapshot_catchup: bool = False
    catchup_threshold_commits: int = 25
    # Helper relay streams (net_sync content-silence/equivocation-gap
    # scoring): the dissemination layer's Byzantine countermeasure — on by
    # default for the matrix; the mixed-version drill turns it off so the
    # old-version half genuinely predates the feature.
    helper_relays: bool = True
    # Mixed-version skew: these nodes additionally run every soft wire tag
    # (timestamped frames, helper streams) the rest of the fleet does not —
    # the rolling-upgrade drill.
    new_version_nodes: Tuple[int, ...] = ()
    # Epoch reconfiguration (reconfig.py): arm Parameters.reconfig, seed the
    # committee with these genesis stakes (() = all ones; a stake-0 entry is
    # a registered-but-inactive authority awaiting a committed ADD), keep
    # ``absent`` authorities unbooted until a churn event joins them, and
    # drive the ``churn`` schedule in BOTH twins — membership change is part
    # of the workload, not a fault, so the clean twin churns identically
    # and the throughput ratio compares like with like.
    reconfig: bool = False
    stakes: Tuple[int, ...] = ()
    absent: Tuple[int, ...] = ()
    churn: Tuple[ChurnEvent, ...] = ()
    # Reconfig gate: the honest fleet must reach at least this epoch by the
    # end of the attacked run (0 = no gate).
    min_epoch: int = 0
    # Execution plane (execution.py): arm Parameters.execution and drive a
    # deterministic account/transfer workload in BOTH twins (execution is
    # workload, not a fault) — every honest node must derive the SAME state
    # root at every shared height or the SafetyChecker fails the run.
    # Each injection batch is self-contained (CREATE a fresh account, then
    # nonce-ordered TRANSFERs out of it in the same proposal), so batches
    # commute across the committed interleaving and rejects stay
    # deterministic.
    execution: bool = False
    exec_interval_s: float = 0.5

    def plan(self) -> FaultPlan:
        return FaultPlan(
            seed=self.seed,
            link_faults=list(self.link_faults),
            partitions=list(self.partitions),
            crashes=list(self.crashes),
            adversaries=list(self.adversaries),
        )

    def clean_plan(self) -> FaultPlan:
        return FaultPlan(seed=self.seed)

    def base_parameters(self) -> Parameters:
        storage = (
            StorageParameters(
                segment_bytes=16 * 1024,
                checkpoint_interval=5,
                gc_depth=30,
                snapshot_catchup=True,
                catchup_threshold_commits=self.catchup_threshold_commits,
            )
            if self.snapshot_catchup
            else StorageParameters()
        )
        return Parameters(
            leader_timeout_s=self.leader_timeout_s,
            reconfig=self.reconfig,
            execution=self.execution,
            # Sim profile: rounds run ~0.1 s, so a 4-round liveness horizon
            # reacts to a silent leader within half a second (the
            # production default of 8 assumes real-network round times).
            leader_liveness_horizon_rounds=4,
            storage=storage,
            synchronizer=SynchronizerParameters(
                disseminate_others_blocks=self.helper_relays,
                # More relay paths per authority: an equivocation variant's
                # arrival is the MIN over its helpers' push paths, and the
                # race it must win (against the children referencing it) is
                # decided in ~half a sim latency draw.
                maximum_helpers_per_authority=4,
            ),
        )

    def per_node_parameters(self) -> Dict[int, Parameters]:
        if not self.new_version_nodes:
            return {}
        base = self.base_parameters()
        upgraded = dataclasses.replace(
            base,
            synchronizer=dataclasses.replace(
                base.synchronizer,
                timestamp_frames=True,
                disseminate_others_blocks=True,
            ),
        )
        return {node: upgraded for node in self.new_version_nodes}

    def latency_ranges(self):
        if self.regions:
            return wan_latency_ranges(list(self.regions))
        if self.latency is not None:
            return {
                (a, b): tuple(self.latency)
                for a in range(self.nodes)
                for b in range(self.nodes)
                if a != b
            }
        return None

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "description": self.description,
            "nodes": self.nodes,
            "duration_s": self.duration_s,
            "seed": self.seed,
            "min_ratio": self.min_ratio,
            "leader_timeout_s": self.leader_timeout_s,
            "regions": list(self.regions),
            "latency": list(self.latency) if self.latency else None,
            "helper_relays": self.helper_relays,
            "snapshot_catchup": self.snapshot_catchup,
            "catchup_threshold_commits": self.catchup_threshold_commits,
            "new_version_nodes": list(self.new_version_nodes),
            "plan": self.plan().to_dict(),
        }
        if self.reconfig:
            # Emitted only for reconfig scenarios so frozen-committee
            # verdict documents stay byte-identical.
            out.update(
                reconfig=True,
                stakes=list(self.stakes),
                absent=list(self.absent),
                churn=[event.to_dict() for event in self.churn],
                min_epoch=self.min_epoch,
            )
        if self.execution:
            # Emitted only for execution scenarios so pre-r20 verdict
            # documents stay byte-identical.
            out.update(
                execution=True,
                exec_interval_s=self.exec_interval_s,
            )
        return out


# ---------------------------------------------------------------------------
# Churn driver


def _churn_driver(scenario: Scenario):
    """The continuous-churn schedule as a chaos ``extra_fault`` hook.

    Runs in BOTH twins (membership change is workload, not attack).  All
    sleeps are virtual time on the :class:`DeterministicLoop`, so the
    schedule is part of the seeded reproduction recipe and same-seed runs
    are byte-identical."""
    events = sorted(scenario.churn, key=lambda e: (e.at_s, e.authority))

    async def driver(harness) -> None:
        now = 0.0
        for event in events:
            if event.at_s > now:
                await asyncio.sleep(event.at_s - now)
                now = event.at_s
            harness.submit_change(
                event.via,
                CommitteeChange(
                    kind=event.kind,
                    authority=event.authority,
                    stake=event.stake,
                ),
            )
            if event.follow_delay_s > 0.0:
                # Let the change ride a proposal and COMMIT before acting on
                # the topology: an ADDed joiner then catches up across the
                # boundary it slept through, and a REMOVEd (possibly
                # leader) node keeps its slots live until the boundary
                # retires them.
                await asyncio.sleep(event.follow_delay_s)
                now += event.follow_delay_s
            if event.kind == CHANGE_ADD and event.authority in harness.absent:
                await harness.join(event.authority)
            elif (
                event.kind == CHANGE_REMOVE
                and harness.nodes[event.authority] is not None
            ):
                await harness.retire(event.authority)

    return driver


# ---------------------------------------------------------------------------
# Execution workload driver


def _exec_driver(scenario: Scenario):
    """Deterministic execution workload as a chaos ``extra_fault`` hook.

    Every ``exec_interval_s`` of virtual time, each live non-adversary node
    plants one SELF-CONTAINED transaction batch on its own block handler:
    CREATE a fresh per-(node, batch) account, TRANSFER out of it twice in
    nonce order, plus one deliberate overdraft (a deterministic typed
    reject folded into the root like any other verdict).  Batches touch
    disjoint accounts, so any committed interleaving applies identically —
    the state-root chain is a pure function of the committed sequence, and
    the SafetyChecker's per-height audit has real state to bite on."""
    from .execution import ExecTx, OP_CREATE, OP_TRANSFER

    async def driver(harness) -> None:
        batch = 0
        while True:
            await asyncio.sleep(scenario.exec_interval_s)
            batch += 1
            for authority in range(scenario.nodes):
                if (
                    authority in harness.checker.adversaries
                    or harness.nodes[authority] is None
                ):
                    continue
                account = f"acct-{authority}-{batch}".encode()
                sink = f"sink-{authority}".encode()
                for tx in (
                    ExecTx(OP_CREATE, account, amount=1000),
                    ExecTx(OP_TRANSFER, account, nonce=1, amount=300,
                           dest=sink),
                    ExecTx(OP_TRANSFER, account, nonce=2, amount=300,
                           dest=b"treasury"),
                    # Overdraft on purpose: 400 left, 500 asked — the typed
                    # reject is part of the deterministic workload.
                    ExecTx(OP_TRANSFER, account, nonce=3, amount=500,
                           dest=sink),
                ):
                    harness.inject(authority, tx.to_bytes())

    return driver


def _compose_drivers(drivers):
    async def driver(harness) -> None:
        await asyncio.gather(*(d(harness) for d in drivers))

    return driver


# ---------------------------------------------------------------------------
# Verdicts


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _sequence_bytes(sequences: Dict[int, list]) -> bytes:
    doc = {
        str(a): [
            f"{ref.authority}:{ref.round}:{ref.digest.hex()}" for ref in seq
        ]
        for a, seq in sorted(sequences.items())
    }
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def _detection_verdicts(
    scenario: Scenario, report: ChaosReport
) -> Dict[str, dict]:
    """Per-adversary detection verdict: which surface caught it.

    ``equivocate`` / ``invalid_sig`` / ``mangle`` have first-class honest-
    side counters; ``withhold`` and ``lag`` are silence-shaped (the honest
    signal is blocks NOT arriving) so their verdict is the attack ledger's
    accounting plus the scenario-level liveness bar."""
    verdicts: Dict[str, dict] = {}
    adversary_nodes = {spec.node for spec in scenario.adversaries}
    for spec in scenario.adversaries:
        key = f"{spec.behavior}:{spec.node}"
        injected = report.attack_counts.get(key, 0)
        detected = 0
        if spec.behavior == "equivocate":
            for a, census in report.detections.items():
                if a in adversary_nodes:
                    continue
                detected += census.get("equivocation", {}).get(
                    f"authority={spec.node}", 0
                )
        elif spec.behavior == "invalid_sig":
            for a, census in report.detections.items():
                if a in adversary_nodes:
                    continue
                detected += census.get("invalid_blocks", {}).get(
                    f"authority={spec.node},reason=signature", 0
                )
        elif spec.behavior == "mangle":
            for a, census in report.detections.items():
                if a in adversary_nodes:
                    continue
                detected += census.get("invalid_blocks", {}).get(
                    f"authority={spec.node},reason=malformed", 0
                )
        verdicts[key] = {
            "behavior": spec.behavior,
            "node": spec.node,
            "injected": injected,
            "detected": int(detected),
            "surface": (
                "ledger"
                if spec.behavior in ("withhold", "lag")
                else spec.behavior
            ),
            "ok": injected > 0
            and (spec.behavior in ("withhold", "lag") or detected > 0),
        }
    return verdicts


def run_scenario(
    scenario: Scenario, wal_root: str, real_crypto: bool = False
) -> dict:
    """Attacked run + clean twin -> the scenario's verdict document.

    ``real_crypto`` swaps the sim re-sign oracle for genuine per-node
    Ed25519 verification (same semantics, minutes instead of seconds on
    the pure-Python fallback — the artifact probe's evidence flag)."""
    committee = Committee.new_for_benchmarks(
        scenario.nodes, stakes=list(scenario.stakes) or None
    )
    kwargs = dict(
        parameters=scenario.base_parameters(),
        per_node_parameters=scenario.per_node_parameters() or None,
        latency_ranges=scenario.latency_ranges(),
        committee=committee,
        with_metrics=True,
        verifier_factory=(
            _real_crypto_factory
            if real_crypto
            else oracle_verifier_factory(scenario.nodes)
        ),
        absent=set(scenario.absent) or None,
    )
    # The churn schedule and the execution workload run in BOTH twins:
    # membership change and state-machine load are part of the workload,
    # so the clean baseline reconfigures and executes identically.
    drivers = []
    if scenario.churn:
        drivers.append(_churn_driver(scenario))
    if scenario.execution:
        drivers.append(_exec_driver(scenario))
    churn = _compose_drivers(drivers) if drivers else None
    attacked_dir = os.path.join(wal_root, f"{scenario.name}-attacked")
    clean_dir = os.path.join(wal_root, f"{scenario.name}-clean")
    os.makedirs(attacked_dir, exist_ok=True)
    os.makedirs(clean_dir, exist_ok=True)
    safety_ok, safety_error = True, None
    report = None
    try:
        report, harness = run_chaos_sim(
            scenario.plan(), scenario.nodes, scenario.duration_s,
            attacked_dir, extra_fault=churn, **kwargs,
        )
    except SafetyViolation as exc:
        safety_ok, safety_error = False, str(exc)
    clean_report, _ = run_chaos_sim(
        scenario.clean_plan(), scenario.nodes, scenario.duration_s,
        clean_dir, extra_fault=churn, **kwargs,
    )
    adversary_nodes = {spec.node for spec in scenario.adversaries}
    honest_nodes = set(range(scenario.nodes)) - adversary_nodes
    clean_leaders = min(
        (len(seq) for a, seq in clean_report.sequences.items()),
        default=0,
    )

    # Honest-AUTHORED committed load on BOTH sides of the ratio: the clean
    # twin's denominator also excludes the (would-be) adversary indices'
    # contributions, so the comparison is like with like — a Byzantine
    # node's own unsequenced transactions are its loss, not the fleet's.
    # Crash-churned nodes are likewise excluded as OBSERVERS (not as
    # authors): a snapshot-rejoiner adopts a baseline and skips settled
    # history BY DESIGN, so its observation window is structurally
    # smaller — its verdict is the explicit catch-up gate below plus the
    # SafetyChecker's adopted-prefix audit, not the throughput min.
    # Churned authorities are excluded the same way: a retired node's
    # committed height freezes at departure and a joiner's observation
    # window starts late — both structural, both gated explicitly below.
    crashed_nodes = (
        {c.node for c in scenario.crashes}
        | set(scenario.absent)
        | {e.authority for e in scenario.churn if e.kind == CHANGE_REMOVE}
    )

    def _honest_min(table: Dict[int, int]) -> int:
        return min(
            (
                table.get(a, 0)
                for a in range(scenario.nodes)
                if a not in adversary_nodes and a not in crashed_nodes
            ),
            default=0,
        )

    clean_tx = _honest_min(clean_report.committed_tx_from(honest_nodes))
    clean_blocks = _honest_min(
        clean_report.committed_blocks_from(honest_nodes)
    )
    verdict: dict = {
        "scenario": scenario.to_dict(),
        "safety_ok": safety_ok,
        "safety_error": safety_error,
        "clean_committed_leaders": clean_leaders,
        "clean_committed_tx": clean_tx,
        "clean_committed_blocks": clean_blocks,
    }
    if report is None:
        verdict.update(
            passed=False, committed_tx=0, committed_blocks=0,
            throughput_ratio=0.0, tx_ratio=0.0,
        )
        return verdict
    honest = {
        a: seq for a, seq in report.sequences.items()
        if a not in adversary_nodes
    }
    committed_leaders = min((len(seq) for seq in honest.values()), default=0)
    committed_tx = _honest_min(report.committed_tx_from(honest_nodes))
    committed = _honest_min(report.committed_blocks_from(honest_nodes))
    # Committed throughput = honest-authored BLOCKS sequenced by the honest
    # prefix: leader-slot skips for silent adversaries cost leader-timeout
    # waits, but honest authorities' blocks still commit under later
    # leaders — exactly what "throughput under attack" should measure.
    # Blocks, not Shares: the sim's TestBlockHandler mints one Share per
    # handle_blocks BATCH, and attacked delivery (relays, fetch) coalesces
    # batches — the Share count under attack under-reports because less
    # load was GENERATED, a test-generator artifact.  The tx ratio rides
    # along as context.
    ratio = committed / clean_blocks if clean_blocks else 0.0
    tx_ratio = committed_tx / clean_tx if clean_tx else 0.0
    detections = _detection_verdicts(scenario, report)
    detections_ok = all(v["ok"] for v in detections.values())
    # Churn gate: every crashed node must have COMMITTED PAST its at-crash
    # height by the end of the run — the explicit rejoin evidence standing
    # in for its excluded observer-min slot (prefix consistency at shared
    # heights is the SafetyChecker's job, including adopted baselines).
    rejoins = [
        {
            "node": event["node"],
            "committed_at_crash": event["committed_height"],
            "committed_final": harness.checker.committed_height(
                event["node"]
            ),
        }
        for event in report.crash_events
    ]
    for rejoin in rejoins:
        rejoin["caught_up"] = (
            rejoin["committed_final"] > rejoin["committed_at_crash"]
        )
    rejoins_ok = all(r["caught_up"] for r in rejoins)
    # Reconfig gate: the honest fleet reached the scheduled epoch (every
    # boundary's height+digest consistency is the SafetyChecker's job —
    # an epoch fork raises, failing safety_ok above), and every joiner
    # actually landed commits on the post-boundary committee.
    reconfig_ok = True
    if scenario.reconfig:
        max_epoch = max(report.epochs.values(), default=0)
        joiner_commits = {
            a: harness.checker.committed_height(a)
            for a in sorted(scenario.absent)
        }
        reconfig_ok = max_epoch >= scenario.min_epoch and all(
            h > 0 for h in joiner_commits.values()
        )
        verdict.update(
            epochs={str(a): e for a, e in sorted(report.epochs.items())},
            epoch_boundaries={
                str(e): b for e, b in sorted(report.epoch_boundaries.items())
            },
            max_epoch=max_epoch,
            min_epoch=scenario.min_epoch,
            joiner_commits={str(a): h for a, h in joiner_commits.items()},
            clean_epochs={
                str(a): e
                for a, e in sorted(clean_report.epochs.items())
            },
            reconfig_ok=reconfig_ok,
        )
    # Execution gate: every steady honest node folded real state (the
    # per-height root agreement itself is the SafetyChecker's job — a
    # state-root fork already failed safety_ok above).  The agreed root
    # chain's digest is the artifact's determinism pin: same-seed runs
    # must reproduce it byte-for-byte.
    execution_ok = True
    if scenario.execution:
        steady = [
            a
            for a in range(scenario.nodes)
            if a not in adversary_nodes and a not in crashed_nodes
        ]
        executed_heights = {
            a: report.executed.get(a, [0, ""])[0] for a in steady
        }
        chain_bytes = json.dumps(
            {str(h): r for h, r in sorted(report.state_root_chain.items())},
            sort_keys=True,
            separators=(",", ":"),
        ).encode()
        execution_ok = bool(report.state_root_chain) and all(
            h > 0 for h in executed_heights.values()
        )
        verdict.update(
            execution={
                "executed_heights": {
                    str(a): h for a, h in sorted(executed_heights.items())
                },
                "chain_length": len(report.state_root_chain),
                "final_root": report.state_root_chain.get(
                    max(report.state_root_chain, default=0), ""
                ),
                "root_chain_digest": _digest(chain_bytes),
                "execution_ok": execution_ok,
            }
        )
    passed = (
        safety_ok
        and detections_ok
        and rejoins_ok
        and reconfig_ok
        and execution_ok
        and ratio >= scenario.min_ratio
        and committed > 0
    )
    verdict.update(
        passed=passed,
        rejoins=rejoins,
        committed_tx=committed_tx,
        committed_blocks=committed,
        committed_leaders=committed_leaders,
        throughput_ratio=round(ratio, 4),
        tx_ratio=round(tx_ratio, 4),
        detections=detections,
        attack_counts=report.attack_counts,
        adversary_divergence=report.adversary_divergence,
        fault_counts=report.fault_counts,
        digests={
            "schedule": report.schedule_digest(),
            "attacks": report.attack_digest(),
            "detections": _digest(report.detections_bytes()),
            "sequences": _digest(_sequence_bytes(report.sequences)),
            "fault_log": _digest(report.fault_log_bytes),
        },
    )
    return verdict


class SimResignOracleVerifier:
    """Exact Ed25519 verification semantics at sim cost: Ed25519 signing is
    deterministic (RFC 8032), and the sim holds every benchmark signer —
    so the correct signature for a digest is *recomputed once per distinct
    block* (memoized fleet-wide) and every node's check is a byte compare.
    A tampered signature (adversary ``invalid_sig``) mismatches exactly as
    under real verification; an equivocating variant, re-signed with the
    real key, matches exactly.  Sim-only by construction (requires the
    private keys); the real-crypto path is exercised by the verifier
    rejection tests and ``tools/scenario_matrix.py --real-crypto``."""

    def __init__(self, committee) -> None:
        from .block_validator import SignatureVerifier

        # Compose rather than subclass so this module stays import-light.
        self._base = SignatureVerifier()
        signers = Committee.benchmark_signers(len(committee))
        self._signer_by_pk = {
            signer.public_key.bytes: signer for signer in signers
        }
        self._memo: Dict[Tuple[bytes, bytes], bytes] = {}

    def verify_signatures(self, public_keys, digests, signatures):
        out = []
        for pk, digest, sig in zip(public_keys, digests, signatures):
            pk, digest = bytes(pk), bytes(digest)
            expected = self._memo.get((pk, digest))
            if expected is None:
                signer = self._signer_by_pk.get(pk)
                if signer is None:
                    out.append(False)
                    continue
                expected = signer.sign(digest)
                self._memo[(pk, digest)] = expected
            out.append(bytes(sig) == expected)
        return out

    def verify_signatures_async(self, public_keys, digests, signatures):
        from .block_validator import DeferredDispatch

        return DeferredDispatch(
            self.verify_signatures, public_keys, digests, signatures
        )

    def __getattr__(self, name):
        # warmup / resolved_backend / padded_batch: the host-oracle
        # defaults.  (verify_signatures* above never reach here.)
        return getattr(self._base, name)


def oracle_verifier_factory(n: int):
    """A scenario-scoped verifier factory: ONE shared re-sign memo across
    the fleet (the point — each distinct block pays one signing), one
    collector per node."""
    oracle_cell: list = []

    def factory(authority, committee, metrics):
        from .block_validator import BatchedSignatureVerifier

        if not oracle_cell:
            oracle_cell.append(SimResignOracleVerifier(committee))
        return BatchedSignatureVerifier(
            committee, oracle_cell[0], max_delay_s=0.002, metrics=metrics
        )

    return factory


def _real_crypto_factory(authority, committee, metrics):
    """Real end-to-end Ed25519 verification through the batching collector
    — the TPU seam with the CPU oracle behind it (deterministic and
    import-light; the kernel-backed flavor is the slow/kernel tier's
    job).  Minutes-per-scenario on the pure-Python fallback: the artifact
    probe's ``--real-crypto`` flag and nothing else."""
    from .block_validator import BatchedSignatureVerifier, CpuSignatureVerifier

    return BatchedSignatureVerifier(
        committee, CpuSignatureVerifier(), max_delay_s=0.002, metrics=metrics
    )


# ---------------------------------------------------------------------------
# The matrix


def default_matrix() -> List[Scenario]:
    """The resilience matrix: >= 5 distinct scenarios composing adversary
    mixes with the chaos / storage / health planes.  Durations are sized
    for the slow tier (~2 sim-runs per scenario on the pure-Python
    Ed25519 fallback); the tier-1 acceptance sim is the byzantine-at-f
    entry at a shorter duration (tests/test_adversary.py)."""
    n = 10
    return [
        Scenario(
            name="byzantine-at-f",
            description=(
                "f=3 of 10 authorities concurrently equivocate, withhold "
                "to < quorum, and sign invalidly — the paper's fault "
                "budget, all attack classes live at once"
            ),
            nodes=n,
            duration_s=20.0,
            seed=7,
            leader_timeout_s=0.3,
            adversaries=(
                AdversarySpec(node=7, behavior="equivocate"),
                AdversarySpec(node=8, behavior="withhold"),
                AdversarySpec(node=9, behavior="invalid_sig"),
            ),
        ),
        Scenario(
            name="byzantine-partition",
            description=(
                "equivocator + invalid signer + frame mangler riding a "
                "timed asymmetric partition: active attack during (and "
                "after) a benign network fault"
            ),
            nodes=n,
            duration_s=16.0,
            seed=21,
            adversaries=(
                AdversarySpec(node=8, behavior="equivocate"),
                AdversarySpec(node=9, behavior="invalid_sig"),
                AdversarySpec(
                    node=7, behavior="mangle", params=(("mangle_p", 0.25),)
                ),
            ),
            partitions=(
                PartitionFault(
                    start_s=3.0, end_s=6.0, group_a=(0, 1),
                    group_b=tuple(range(2, n)), symmetric=False,
                ),
            ),
            min_ratio=0.6,
        ),
        Scenario(
            name="churn-snapshot-rejoin",
            description=(
                "a node crashes long enough that its history is GC'd "
                "fleet-wide and rejoins via the snapshot stream WHILE an "
                "equivocator attacks — catch-up under fire"
            ),
            nodes=5,
            duration_s=40.0,
            seed=13,
            adversaries=(AdversarySpec(node=4, behavior="equivocate"),),
            crashes=(CrashFault(node=3, at_s=3.0, downtime_s=22.0),),
            snapshot_catchup=True,
            catchup_threshold_commits=25,
            # During the outage the live committee is EXACTLY quorum (4 of
            # 5, one of them the equivocator), so every cross-half variant
            # relay sits on the round critical path — the scenario's heart
            # is the rejoin gate + safety under attack; the ratio floor
            # accepts the zero-margin phase's round-rate cost.
            min_ratio=0.5,
        ),
        Scenario(
            name="wan-geo-profile",
            description=(
                "three-region WAN latency profile (5-15 ms intra, "
                "80-160 ms inter) with a lagging leader and a withholder "
                "— grey failures at geographic latency"
            ),
            nodes=9,
            duration_s=12.0,
            seed=31,
            leader_timeout_s=2.0,
            regions=(0, 0, 0, 1, 1, 1, 2, 2, 2),
            adversaries=(
                AdversarySpec(
                    node=7, behavior="lag", params=(("lag_s", 1.6),)
                ),
                AdversarySpec(node=8, behavior="withhold"),
            ),
            min_ratio=0.6,
        ),
        Scenario(
            name="mixed-version-skew",
            description=(
                "half the fleet runs every soft wire tag (timestamped "
                "frames, helper streams) the other half predates, under "
                "an invalid signer and link loss — the rolling-upgrade "
                "drill"
            ),
            nodes=n,
            duration_s=12.0,
            seed=42,
            adversaries=(AdversarySpec(node=9, behavior="invalid_sig"),),
            link_faults=(
                LinkFault(drop_p=0.02, start_s=0.0),
            ),
            helper_relays=False,
            new_version_nodes=(0, 2, 4, 6, 8),
            # The clean twin strips the 2% link loss, and the OLD half
            # recovers dropped blocks only via reactive fetch (no helper
            # relays — that is the drill's point), so the floor prices the
            # benign-loss recovery cost; the drill's verdict is interop
            # (soft tags ignored cleanly both ways) + detection + safety.
            min_ratio=0.5,
        ),
    ]


def reconfig_matrix() -> List[Scenario]:
    """The continuous-churn scenario family (epoch reconfiguration plane):
    dynamic membership driven through the committed sequence, in every
    case with the identical churn schedule in the clean twin.  Stable-
    index membership: all ten authorities are registered at genesis; an
    absent joiner starts at stake 0 and a committed ADD activates it."""
    n = 10
    return [
        Scenario(
            name="reconfig-continuous-churn",
            description=(
                "three epoch transitions under attack: a stake reweight, "
                "an ADD that a genesis-absent authority joins through the "
                "snapshot stream (its manifest carries the epoch chain), "
                "and a REMOVE that cleanly retires a live node — all "
                "while an equivocator attacks"
            ),
            nodes=n,
            duration_s=24.0,
            seed=18,
            leader_timeout_s=0.3,
            adversaries=(AdversarySpec(node=7, behavior="equivocate"),),
            snapshot_catchup=True,
            catchup_threshold_commits=25,
            reconfig=True,
            stakes=(1, 1, 1, 1, 1, 1, 1, 1, 1, 0),
            absent=(9,),
            churn=(
                ChurnEvent(
                    at_s=3.0, kind=CHANGE_REWEIGHT, authority=2, stake=3
                ),
                ChurnEvent(
                    at_s=7.0,
                    kind=CHANGE_ADD,
                    authority=9,
                    stake=1,
                    follow_delay_s=3.0,
                ),
                ChurnEvent(at_s=13.0, kind=CHANGE_REMOVE, authority=8),
            ),
            min_epoch=3,
            min_ratio=0.5,
        ),
        Scenario(
            name="reconfig-departing-leader",
            description=(
                "a frequently-elected leader is REMOVEd mid-run and "
                "departs cleanly after the boundary retires its slots, "
                "while a withholder attacks — commit cadence must carry "
                "across the committee switch without a liveness stall"
            ),
            nodes=n,
            duration_s=14.0,
            seed=77,
            leader_timeout_s=0.3,
            adversaries=(AdversarySpec(node=6, behavior="withhold"),),
            reconfig=True,
            churn=(
                ChurnEvent(
                    at_s=5.0,
                    kind=CHANGE_REMOVE,
                    authority=1,
                    follow_delay_s=2.5,
                ),
            ),
            min_epoch=1,
            min_ratio=0.5,
        ),
        Scenario(
            name="reconfig-cross-boundary-rejoin",
            description=(
                "a genesis-absent authority sleeps through TWO boundaries "
                "(a reweight, then a REMOVE) before its own ADD lands; it "
                "then boots from an empty WAL and must land on the "
                "epoch-3 committee via the snapshot epoch chain, under an "
                "invalid-signing adversary"
            ),
            nodes=n,
            duration_s=26.0,
            seed=5,
            leader_timeout_s=0.3,
            adversaries=(AdversarySpec(node=6, behavior="invalid_sig"),),
            snapshot_catchup=True,
            catchup_threshold_commits=25,
            reconfig=True,
            stakes=(1, 1, 1, 1, 1, 1, 1, 1, 1, 0),
            absent=(9,),
            churn=(
                ChurnEvent(
                    at_s=3.0, kind=CHANGE_REWEIGHT, authority=3, stake=2
                ),
                ChurnEvent(at_s=6.0, kind=CHANGE_REMOVE, authority=8),
                ChurnEvent(
                    at_s=11.0,
                    kind=CHANGE_ADD,
                    authority=9,
                    stake=1,
                    follow_delay_s=3.0,
                ),
            ),
            min_epoch=3,
            min_ratio=0.5,
        ),
    ]


def execution_matrix() -> List[Scenario]:
    """The execution-plane scenario family: the deterministic
    account/transfer state machine folding the committed sequence under the
    adversary matrix and under epoch churn.  Every honest node must derive
    the same state-root chain (the SafetyChecker's per-height audit) and
    the verdict pins the chain digest so same-seed runs must reproduce it
    byte-for-byte."""
    n = 10
    return [
        Scenario(
            name="execution-byzantine-at-f",
            description=(
                "the byzantine-at-f adversary mix (equivocate + withhold + "
                "invalid_sig at f=3 of 10) with the execution state "
                "machine live: honest state roots must agree at every "
                "shared height — consensus-level attacks must never "
                "diverge replicated state"
            ),
            nodes=n,
            duration_s=16.0,
            seed=7,
            leader_timeout_s=0.3,
            adversaries=(
                AdversarySpec(node=7, behavior="equivocate"),
                AdversarySpec(node=8, behavior="withhold"),
                AdversarySpec(node=9, behavior="invalid_sig"),
            ),
            execution=True,
            min_ratio=0.5,
        ),
        Scenario(
            name="execution-epoch-churn",
            description=(
                "execution workload across two epoch transitions (a stake "
                "reweight and a clean REMOVE) under an equivocator: the "
                "state-root chain must carry across committee switches "
                "unbroken"
            ),
            nodes=n,
            duration_s=18.0,
            seed=18,
            leader_timeout_s=0.3,
            adversaries=(AdversarySpec(node=7, behavior="equivocate"),),
            reconfig=True,
            execution=True,
            churn=(
                ChurnEvent(
                    at_s=4.0, kind=CHANGE_REWEIGHT, authority=2, stake=3
                ),
                ChurnEvent(
                    at_s=9.0,
                    kind=CHANGE_REMOVE,
                    authority=8,
                    follow_delay_s=2.5,
                ),
            ),
            min_epoch=2,
            min_ratio=0.5,
        ),
    ]


def scenario_by_name(name: str) -> Scenario:
    matrix = default_matrix() + reconfig_matrix() + execution_matrix()
    for scenario in matrix:
        if scenario.name == name:
            return scenario
    raise KeyError(
        f"unknown scenario {name!r} "
        f"(known: {', '.join(s.name for s in matrix)})"
    )


def run_reconfig_matrix(
    scenarios: Optional[List[Scenario]] = None,
    wal_root: Optional[str] = None,
    real_crypto: bool = False,
) -> dict:
    """Run the continuous-churn family and aggregate the RECONFIG artifact
    document (tools/reconfig_matrix.py pins it into RECONFIG_rNN.json)."""
    doc = run_matrix(
        scenarios if scenarios is not None else reconfig_matrix(),
        wal_root=wal_root,
        real_crypto=real_crypto,
    )
    doc["kind"] = "mysticeti-reconfig-matrix"
    doc["metric"] = "reconfig"
    return doc


def run_matrix(
    scenarios: Optional[List[Scenario]] = None,
    wal_root: Optional[str] = None,
    real_crypto: bool = False,
) -> dict:
    """Run the matrix and aggregate the artifact document."""
    import tempfile

    scenarios = scenarios if scenarios is not None else default_matrix()
    own_root = wal_root is None
    wal_root = wal_root or tempfile.mkdtemp(prefix="scenario-matrix-")
    results = []
    for scenario in scenarios:
        log.info("scenario %s: running", scenario.name)
        verdict = run_scenario(scenario, wal_root, real_crypto=real_crypto)
        log.info(
            "scenario %s: %s (ratio %.2f)", scenario.name,
            "PASS" if verdict["passed"] else "FAIL",
            verdict.get("throughput_ratio", 0.0),
        )
        results.append(verdict)
    if own_root:
        import shutil

        shutil.rmtree(wal_root, ignore_errors=True)
    return {
        "kind": "mysticeti-scenario-matrix",
        "metric": "scenario_matrix",
        "verifier": "real-crypto" if real_crypto else "sim-resign-oracle",
        "scenarios": results,
        "passed": sum(1 for r in results if r["passed"]),
        "failed": sum(1 for r in results if not r["passed"]),
        "all_pass": all(r["passed"] for r in results),
    }
