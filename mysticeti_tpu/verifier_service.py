"""Shared per-host verifier service: ONE warmed JAX runtime for the fleet.

Round-4 finding: giving every validator process its own JAX runtime
(``validator.py:_make_verifier``) made the TPU path lose to CPU at fleet
level — N processes serially paying import + PJRT init + trace/compile on a
shared host, then N independent connections to the accelerator.  The
reference never hits this because its verifier is a CPU function in-process
(``mysticeti-core/src/crypto.rs:174-189``); a TPU-first design wants the
opposite split: the accelerator runtime is a HOST resource, owned by one
process, shared by every co-located validator.

  * :class:`VerifierServer` — owns a single :class:`TpuSignatureVerifier`
    (one PJRT client, one compile cache, warmed once), serves signature
    batches over a unix-domain socket.  Requests from different validators
    dispatch concurrently (async device dispatch overlaps their round-trips).
  * :class:`RemoteSignatureVerifier` — the validator-side
    :class:`SignatureVerifier` that forwards batches to the service.  It
    never imports jax: a validator process using it boots import-light, and
    a REBOOTED validator re-attaches to the still-warm service instead of
    re-paying a cold runtime (the round-4 catch-up gap: 100 s+ of re-warm).

Wire protocol (little-endian, length-prefixed frames):

  frame    = u32 payload_len | u8 type | payload
  HELLO    (1)   u16 n_keys | n_keys * 32 B pk      -> HELLO_OK once warm
  VERIFY   (2)   u32 req_id | u32 n | n * (u16 key_idx | 32 B digest | 64 B sig)
  RAW      (3)   u32 req_id | u32 n | n * (32 B pk | 32 B digest | 64 B sig)
  HELLO_OK (128) f64 fixed_dispatch_s | f64 per_sig_s   (empty = uncalibrated)
  RESULT   (129) u32 req_id | n * u8 ok
  ERR      (255) utf-8 message (protocol error; connection closes)

HELLO doubles as the warmup gate: the reply is sent only after the backend's
one-time trace/compile finished, so a client's ``warmup()`` is "send HELLO,
wait" — seconds against a warm service, never minutes.  All clients must
present the same committee (one table per service); a mismatch is an ERR.

HELLO_OK carries the service's OWN dispatch calibration (a timed 1-signature
and batch dispatch after warmup): the hybrid router needs (fixed, per-sig)
cost estimates, and N validators each probing a shared-host service would
serialize N probe dispatches behind fleet boot contention — measured on a
1-core host, 5 of 7 validators were still waiting for their probe a minute
in.  One server-side measurement, taken once on an idle backend, is both
cheaper and more accurate.
"""
from __future__ import annotations

import asyncio
import itertools
import os
import random
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from .block_validator import (
    CpuSignatureVerifier,
    SignatureVerifier,
    VerifierProtocolError,
)
from .network import jittered_backoff
from .verify_pipeline import CompletedDispatch, DeferredDispatch
from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)

T_HELLO = 1
T_VERIFY = 2
T_RAW = 3
T_HELLO_OK = 128
T_RESULT = 129
T_ERR = 255

_IDX_REC = 2 + 32 + 64  # u16 idx | digest | sig
_RAW_REC = 32 + 32 + 64

ENV_SOCKET = "MYSTICETI_VERIFIER_SOCKET"

# VerifierProtocolError (re-exported above from block_validator): the service
# answered but REJECTED the request.  Excluded from the client's retry loop
# AND from the hybrid circuit breaker — a misconfigured validator fails fast
# instead of hammering the service or silently degrading to the oracle.


def _frame(type_: int, payload: bytes) -> bytes:
    return struct.pack("<IB", len(payload), type_) + payload


def _abandoned_reply(fut: asyncio.Future, cleanup) -> None:
    """Completion hook for a dispatch whose connection died before its reply
    could be written: retrieve the exception (so asyncio never logs it as
    never-retrieved at GC) and only then release the service gauges."""
    if not fut.cancelled() and fut.exception() is not None:
        log.error(
            "verifier service dispatch failed after client disconnect",
            exc_info=fut.exception(),
        )
    if cleanup is not None:
        cleanup()


# ---------------------------------------------------------------------------
# Server


class VerifierServer:
    """One accelerator runtime serving every validator on the host."""

    # Per-connection staged request window: the reader decodes request N+1
    # while N computes in the pool; replies are written strictly in request
    # order by a dedicated writer task.  The bound backpressures a client
    # pipelining faster than the backend drains.
    PIPELINE_DEPTH = 8

    def __init__(self, socket_path: str, committee_keys: Optional[Sequence[bytes]] = None,
                 backend=None, metrics=None) -> None:
        self.socket_path = socket_path
        self._backend = backend
        self._owns_backend = backend is None
        self._keys: Optional[List[bytes]] = (
            list(committee_keys) if committee_keys else None
        )
        # Optional Metrics: queue depth / per-connection in-flight gauges +
        # dispatch shape series, scrapeable when the service CLI runs with
        # --metrics-port (the fleet's verify queue was invisible before).
        self.metrics = metrics
        self._conn_ids = itertools.count()
        self._warmed = threading.Event()
        self._warm_lock = threading.Lock()
        # Sized for a 10+ validator fleet: each in-flight request blocks a
        # worker thread on the device fetch, and overlapping those
        # round-trips is the entire point of sharing the runtime.
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="verify-dispatch"
        )
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()
        self._calibration: Optional[Tuple[float, float]] = None

    # -- backend lifecycle --

    def _ensure_backend(self, keys: List[bytes]):
        # The whole init+warmup runs under the lock: concurrent HELLOs from a
        # booting fleet must not race two warmups through the JAX tracer —
        # the losers just block here until the first one finishes (which is
        # exactly the contract their HELLO wants anyway).
        with self._warm_lock:
            if keys:
                if self._keys is None:
                    # First NON-EMPTY committee establishes the service key
                    # set (ADVICE r5: an early zero-key HELLO from a RAW-only
                    # client must not pin the committee to [] and poison
                    # every later client with a permanent mismatch).  If a
                    # keyless backend was already built for such a client,
                    # rebuild it around the real committee's key table.
                    self._keys = keys
                    if self._backend is not None and self._owns_backend:
                        self._backend = None
                        self._warmed.clear()
                elif self._keys != keys:
                    raise ValueError(
                        "committee mismatch: this verifier service was warmed "
                        "for a different key set"
                    )
            if self._backend is None:
                from .block_validator import TpuSignatureVerifier

                self._backend = TpuSignatureVerifier(committee_keys=self._keys)
                self._owns_backend = True
            if not self._warmed.is_set():
                self._backend.warmup()
                self._calibrate()
                self._warmed.set()
            return self._backend

    def _calibrate(self) -> None:
        """Time the warmed backend once: a 1-signature dispatch (fixed cost)
        and a 256-signature dispatch (marginal cost), on the deployed
        committee-indexed path.  Shared with every client via HELLO_OK."""
        import time

        keys = self._keys or []
        if not keys:
            return
        pk = keys[0]
        digest = bytes(32)
        sig = bytes(64)
        try:
            t0 = time.monotonic()
            self._backend.verify_signatures([pk], [digest], [sig])
            fixed = time.monotonic() - t0
            n = 256
            t0 = time.monotonic()
            self._backend.verify_signatures(
                [keys[i % len(keys)] for i in range(n)],
                [digest] * n, [sig] * n,
            )
            batch_t = time.monotonic() - t0
            self._calibration = (fixed, max(0.0, (batch_t - fixed) / n))
            log.info(
                "verifier service calibrated: %.1f ms fixed + %.1f µs/sig",
                1e3 * self._calibration[0], 1e6 * self._calibration[1],
            )
        except Exception:  # calibration is advisory, never fatal
            log.exception("verifier service calibration failed")

    def prewarm(self) -> None:
        """Warm before the first client connects (committee known at boot)."""
        if self._keys is None:
            raise ValueError("prewarm requires committee keys")
        self._ensure_backend(self._keys)

    # -- connection handling --

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # Staged per-connection request pipeline: the reader decodes and
        # submits request N+1 while request N computes in the pool; a
        # dedicated writer task emits replies strictly in request order (the
        # protocol contract clients rely on), so the service is no longer a
        # stop-and-wait RPC for a client that pipelines its frames.
        loop = asyncio.get_running_loop()
        self._writers.add(writer)
        conn_label = f"c{next(self._conn_ids)}"
        replies: asyncio.Queue = asyncio.Queue(maxsize=self.PIPELINE_DEPTH)
        reply_task = spawn_logged(
            self._reply_writer(replies, writer), log, name="verifier-replies"
        )

        def _accounted():
            metrics = self.metrics
            if metrics is None:
                return None
            # Depth = requests handed to the pool and not yet answered
            # (queued behind the 16 workers or mid-dispatch); inflight
            # splits it per client connection so one flooding validator is
            # attributable.  Decremented by the writer once the reply is
            # built (cleanup runs even when the dispatch raised).
            metrics.verifier_service_queue_depth.inc()
            metrics.verifier_service_inflight.labels(conn_label).inc()

            def _done():
                metrics.verifier_service_queue_depth.dec()
                metrics.verifier_service_inflight.labels(conn_label).dec()

            return _done

        # A pipelined client may send VERIFY frames behind a HELLO without
        # waiting for HELLO_OK; pool threads run jobs in any order, so a
        # verify must not EXECUTE before the HELLO that establishes the
        # committee finished (it would see no keys and report every slot
        # invalid).  Replies stay ordered by the queue; execution is gated
        # on the connection's last unresolved HELLO only.
        last_hello: Optional[asyncio.Future] = None

        async def _after_hello(gate, type_, req_id, n, body):
            try:
                hello_frame = await asyncio.shield(gate)
            except Exception:  # noqa: BLE001 - HELLO's own reply carries it
                hello_frame = None
            if hello_frame is None or hello_frame[4] == T_ERR:
                # The HELLO was rejected (committee mismatch) or crashed:
                # the connection is being severed and this reply would be
                # discarded in drain mode — do NOT burn a backend dispatch
                # for it (a reconnect-looping misconfigured client would
                # otherwise cost a device round-trip per queued frame).
                return None
            return await loop.run_in_executor(
                self._pool, self._result_reply, type_, req_id, n, body
            )

        try:
            while True:
                try:
                    header = await reader.readexactly(5)
                except asyncio.IncompleteReadError:
                    return
                if reply_task.done():
                    return  # writer died (client gone, backend crash)
                length, type_ = struct.unpack("<IB", header)
                payload = await reader.readexactly(length) if length else b""
                if type_ == T_HELLO:
                    n_keys = (
                        struct.unpack_from("<H", payload)[0]
                        if length >= 2 else -1
                    )
                    if n_keys < 0 or length != 2 + 32 * n_keys:
                        await replies.put(
                            (_frame(T_ERR, b"malformed hello frame"),
                             None, True)
                        )
                        return
                    keys = [
                        bytes(payload[2 + 32 * i: 2 + 32 * (i + 1)])
                        for i in range(n_keys)
                    ]
                    # HELLO replies ride the same in-order queue as results:
                    # a client that pipelines frames must never see HELLO_OK
                    # overtake an earlier RESULT.
                    fut = loop.run_in_executor(
                        self._pool, self._hello_reply, keys
                    )
                    last_hello = fut
                    await replies.put((fut, None, False))
                elif type_ in (T_VERIFY, T_RAW):
                    if length < 8:
                        await replies.put(
                            (_frame(T_ERR, b"malformed verify frame"),
                             None, True)
                        )
                        return
                    req_id, n = struct.unpack_from("<II", payload)
                    body = payload[8:]
                    rec = _IDX_REC if type_ == T_VERIFY else _RAW_REC
                    if len(body) != n * rec:
                        await replies.put(
                            (_frame(T_ERR, b"malformed verify frame"),
                             None, True)
                        )
                        return
                    if last_hello is not None and last_hello.done():
                        rejected = last_hello.cancelled() or (
                            last_hello.exception() is not None
                            or last_hello.result()[4] == T_ERR
                        )
                        if rejected:
                            # The writer is severing after the HELLO's ERR:
                            # frames pipelined behind it must not burn
                            # backend dispatches for replies that will be
                            # discarded in drain mode.
                            return
                        last_hello = None  # accepted: no more gating needed
                    done = _accounted()
                    if last_hello is not None:
                        # Awaited by the reply writer in order, which
                        # observes its exception.  # lint: ignore[task-orphan]
                        fut = asyncio.ensure_future(
                            _after_hello(last_hello, type_, req_id, n, body)
                        )
                    else:
                        fut = loop.run_in_executor(
                            self._pool, self._result_reply,
                            type_, req_id, n, body,
                        )
                    await replies.put((fut, done, False))
                else:
                    await replies.put(
                        (_frame(T_ERR, b"unknown frame type"), None, True)
                    )
                    return
        except (ConnectionResetError, BrokenPipeError, OSError):
            return
        finally:
            # Let the writer drain everything already submitted, then stop.
            try:
                replies.put_nowait(None)
            except asyncio.QueueFull:
                reply_task.cancel()
            try:
                await reply_task
            except asyncio.CancelledError:
                reply_task.cancel()
            except Exception:  # noqa: BLE001 - writer logged its own failure
                pass
            # Anything left unqueued-for-write still owes its cleanup, but
            # its dispatch may still be running on a pool thread: releasing
            # the gauges now would show an idle service during real device
            # work, and abandoning the future would leave its exception
            # unretrieved.  Defer both to the dispatch's own completion.
            abandoned = []
            while True:
                try:
                    item = replies.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if item is None:
                    continue
                frame, cleanup, _close_after = item
                if asyncio.isfuture(frame):
                    abandoned.append((frame, cleanup))
                elif cleanup is not None:
                    cleanup()

            def _remove_label() -> None:
                # Labels are minted per connection from an unbounded counter;
                # a reconnecting fleet would otherwise grow dead
                # {connection="cN"} series in the registry forever.
                if self.metrics is not None:
                    try:
                        self.metrics.verifier_service_inflight.remove(
                            conn_label
                        )
                    except KeyError:
                        pass  # connection closed before its first verify

            if abandoned:
                # The label must outlive every deferred cleanup: a dec()
                # after remove() would re-mint the dead series at -1 and
                # leak it forever.  The LAST abandoned dispatch to complete
                # removes it (done-callbacks run on the loop thread, so the
                # countdown needs no lock).
                remaining = {"n": len(abandoned)}

                def _finish(fut, cleanup) -> None:
                    _abandoned_reply(fut, cleanup)
                    remaining["n"] -= 1
                    if remaining["n"] == 0:
                        _remove_label()

                for fut, cleanup in abandoned:
                    fut.add_done_callback(
                        lambda f, cleanup=cleanup: _finish(f, cleanup)
                    )
            else:
                _remove_label()
            self._writers.discard(writer)
            writer.close()

    async def _reply_writer(self, replies: asyncio.Queue,
                            writer: asyncio.StreamWriter) -> None:
        """Emit queued replies in request order; ``None`` ends the stream.
        Queue items are ``(frame_or_future, cleanup, close_after)``.  A
        dispatch failure or a dead client socket flips to drain mode —
        remaining cleanups still run (gauge hygiene) but nothing is written,
        and the transport is closed so the reader unblocks."""
        dead = False
        while True:
            item = await replies.get()
            if item is None:
                return
            frame, cleanup, close_after = item
            try:
                if asyncio.isfuture(frame):
                    try:
                        frame = await frame
                    except Exception:  # noqa: BLE001 - logged, conn severed
                        log.exception("verifier service dispatch failed")
                        frame = None
                if dead or frame is None:
                    dead = True
                    writer.close()
                    continue
                if frame[4] == T_ERR:
                    # Protocol errors sever the connection after the reply
                    # (the pre-pipeline contract), wherever they were built.
                    close_after = True
                try:
                    writer.write(frame)
                    await writer.drain()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    dead = True
                    continue
                if close_after:
                    dead = True
                    writer.close()
            finally:
                if cleanup is not None:
                    cleanup()

    def _hello_reply(self, keys: List[bytes]) -> bytes:
        """Pool-side HELLO handling: warm (or adopt/upgrade) the backend and
        frame the reply — HELLO_OK with the calibration, or ERR on a
        committee mismatch (which also severs the connection client-side)."""
        try:
            self._ensure_backend(keys)
        except ValueError as exc:
            return _frame(T_ERR, str(exc).encode())
        calibration = b""
        if self._calibration is not None:
            calibration = struct.pack("<dd", *self._calibration)
        return _frame(T_HELLO_OK, calibration)

    def _result_reply(self, type_: int, req_id: int, n: int,
                      body: bytes) -> bytes:
        oks = self._verify_payload(type_, n, body)
        return _frame(T_RESULT, struct.pack("<I", req_id) + bytes(oks))

    def _verify_payload(self, type_: int, n: int, body: bytes) -> List[int]:
        backend = self._ensure_backend(self._keys or [])
        pks, digests, sigs = [], [], []
        if type_ == T_VERIFY:
            keys = self._keys or []
            for i in range(n):
                off = i * _IDX_REC
                (idx,) = struct.unpack_from("<H", body, off)
                if idx >= len(keys):
                    # An out-of-range index cannot verify; reject that slot
                    # rather than the whole batch.
                    pks.append(bytes(32))
                else:
                    pks.append(keys[idx])
                digests.append(body[off + 2: off + 34])
                sigs.append(body[off + 34: off + 98])
        else:
            for i in range(n):
                off = i * _RAW_REC
                pks.append(body[off: off + 32])
                digests.append(body[off + 32: off + 64])
                sigs.append(body[off + 64: off + 128])
        oks = backend.verify_signatures(pks, digests, sigs)
        if self.metrics is not None:
            # The service owns the device, so it (not the jax-free clients)
            # is where dispatch shape and padding waste are measurable.
            self.metrics.verify_dispatch_batch_size.observe(n)
            padder = getattr(backend, "padded_batch", None)
            if padder is not None:
                self.metrics.verify_padding_wasted_total.labels(
                    "service"
                ).inc(max(0, padder(n) - n))
        return [1 if ok else 0 for ok in oks]

    # -- lifecycle --

    async def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._server = await asyncio.start_unix_server(
            self._handle, path=self.socket_path
        )
        log.info("verifier service listening on %s", self.socket_path)

    async def serve_forever(self) -> None:
        await self.start()
        if self._keys is not None and not self._warmed.is_set():
            # Warm while validators boot: their HELLOs block until done.
            await asyncio.get_running_loop().run_in_executor(
                self._pool, self.prewarm
            )
            log.info("verifier service warmed (%d committee keys)",
                     len(self._keys))
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Sever live client connections first: since 3.12,
            # ``wait_closed`` waits for every connection HANDLER to finish,
            # and handlers block in readexactly on idle-but-open clients.
            for writer in list(self._writers):
                writer.close()
            await self._server.wait_closed()
        self._pool.shutdown(wait=False)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)


# ---------------------------------------------------------------------------
# Client


class RemoteSignatureVerifier(SignatureVerifier):
    """Validator-side stub: forwards batches to the host's verifier service.

    jax-free by design — the validator process stays import-light and leans
    on the service's single warmed runtime.  Called from the batching
    collector's executor threads: each thread keeps its own connection
    (``threading.local``) so concurrent flushes pipeline through the service
    rather than serializing on one socket.
    """

    backend_label = "tpu-remote"

    # Reconnect-retry budget per request: a service restart mid-burst is
    # routine (seconds of downtime), a fleet boot race is routine — neither
    # is an outage.  Only exhausting the budget propagates, and the hybrid
    # circuit breaker takes it from there.
    MAX_ATTEMPTS = 4
    RETRY_BASE_BACKOFF_S = 0.05
    RETRY_MAX_BACKOFF_S = 1.0

    # Bound on idle pooled connections for the async dispatch path; matches
    # the deepest pipeline window the collector runs (verify_pipeline.py).
    MAX_POOLED_CONNS = 4

    def __init__(self, socket_path: Optional[str] = None,
                 committee_keys: Optional[Sequence[bytes]] = None,
                 timeout_s: float = 300.0,
                 metrics=None,
                 max_attempts: Optional[int] = None) -> None:
        self.socket_path = socket_path or os.environ[ENV_SOCKET]
        self._keys = list(committee_keys or [])
        self._index = {pk: i for i, pk in enumerate(self._keys)}
        self.timeout_s = timeout_s
        self.metrics = metrics
        self.max_attempts = max_attempts or self.MAX_ATTEMPTS
        self._retry_rng = random.Random(0x5E7C1E27)
        self._tls = threading.local()
        # Connection pool for the STAGED path (verify_signatures_async): the
        # submit and the fetch may run on different executor threads, so the
        # in-flight handle carries its connection instead of leaning on the
        # thread-local one.  _pool_size counts live pooled conns (idle +
        # checked out) so the pool stays bounded across threads.
        self._pool_conns: List[socket.socket] = []
        self._pool_lock = threading.Lock()
        self._pool_size = 0
        self._async_req_ids = itertools.count(1)
        # (fixed_dispatch_s, per_sig_s) as measured by the SERVICE on its
        # own warmed backend (HELLO_OK payload); None until first connect.
        self.calibration: Optional[Tuple[float, float]] = None

    # -- socket plumbing --

    def _connect(self) -> socket.socket:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(self.timeout_s)
        conn.connect(self.socket_path)
        payload = struct.pack("<H", len(self._keys)) + b"".join(self._keys)
        conn.sendall(_frame(T_HELLO, payload))
        type_, reply = self._read_frame(conn)
        if type_ != T_HELLO_OK:
            conn.close()
            raise VerifierProtocolError(
                f"verifier service rejected hello: {reply.decode(errors='replace')}"
            )
        if len(reply) == 16:
            self.calibration = struct.unpack("<dd", reply)
        return conn

    def dispatch_calibration(self) -> Optional[Tuple[float, float]]:
        """Server-measured (fixed_s, per_sig_s) — the hybrid router's cost
        model, without every client paying its own probe dispatch."""
        return self.calibration

    def _conn(self) -> socket.socket:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = self._connect()
            self._tls.conn = conn
            self._tls.req_id = 0
        return conn

    @staticmethod
    def _read_frame(conn: socket.socket):
        header = b""
        while len(header) < 5:
            chunk = conn.recv(5 - len(header))
            if not chunk:
                raise ConnectionError("verifier service closed the connection")
            header += chunk
        length, type_ = struct.unpack("<IB", header)
        payload = b""
        while len(payload) < length:
            chunk = conn.recv(length - len(payload))
            if not chunk:
                raise ConnectionError("verifier service closed mid-frame")
            payload += chunk
        return type_, payload

    def _roundtrip(self, frame: bytes, req_id: int) -> bytes:
        """Send one request with bounded reconnect-retries.

        The round-5 reconnect-ONCE policy made a service restart during a
        fleet burst a fatal outage: every in-flight thread burned its single
        retry against the not-yet-listening socket and propagated.  Retries
        are bounded (``max_attempts``) with jittered exponential backoff so
        a thundering herd of dispatch threads does not hammer the recovering
        service in lockstep; each torn-down connection counts on
        ``verifier_reconnect_total``.  Protocol rejections
        (:class:`VerifierProtocolError`) are never retried, and exhausting
        the budget propagates — the hybrid circuit breaker takes it from
        there."""
        backoff = self.RETRY_BASE_BACKOFF_S
        for attempt in range(self.max_attempts):
            try:
                conn = self._conn()
                conn.sendall(frame)
                type_, payload = self._read_frame(conn)
                break
            except VerifierProtocolError:
                raise
            except (ConnectionError, OSError, socket.timeout):
                stale = getattr(self._tls, "conn", None)
                self._tls.conn = None
                if stale is not None:
                    try:
                        stale.close()
                    except OSError:
                        pass
                if self.metrics is not None:
                    self.metrics.verifier_reconnect_total.inc()
                if attempt + 1 >= self.max_attempts:
                    raise
                time.sleep(jittered_backoff(backoff, self._retry_rng))
                backoff = min(backoff * 2.0, self.RETRY_MAX_BACKOFF_S)
        if type_ == T_ERR:
            raise VerifierProtocolError(
                f"verifier service error: {payload.decode(errors='replace')}"
            )
        assert type_ == T_RESULT
        (echoed,) = struct.unpack_from("<I", payload)
        assert echoed == req_id, "verifier service response out of order"
        return payload[4:]

    # -- connection pool (async dispatch path) --

    def _pool_checkout(self) -> Optional[socket.socket]:
        """An idle pooled connection, a fresh one, or None when the pool is
        at its live-connection cap (idle + checked out) — the caller then
        falls back to the sync path's thread-local connection."""
        with self._pool_lock:
            if self._pool_conns:
                return self._pool_conns.pop()
            if self._pool_size >= self.MAX_POOLED_CONNS:
                return None
            self._pool_size += 1
        try:
            return self._connect()
        except BaseException:
            with self._pool_lock:
                self._pool_size -= 1
            raise

    def _pool_checkin(self, conn: socket.socket) -> None:
        with self._pool_lock:
            if len(self._pool_conns) < self.MAX_POOLED_CONNS:
                self._pool_conns.append(conn)
                return
            self._pool_size -= 1
        try:
            conn.close()
        except OSError:
            pass

    def _pool_discard(self, conn: socket.socket) -> None:
        with self._pool_lock:
            self._pool_size -= 1
        try:
            conn.close()
        except OSError:
            pass

    # -- frame building --

    def _build_frame(self, public_keys, digests, signatures, req_id, n):
        """Pack one request frame, or None when the batch cannot ride the
        service wire format (non-digest messages -> local oracle)."""
        indices = [self._index.get(pk) for pk in public_keys]
        if all(i is not None for i in indices) and all(
            len(d) == 32 for d in digests
        ):
            body = b"".join(
                struct.pack("<H", idx) + digest + sig
                for idx, digest, sig in zip(indices, digests, signatures)
            )
            return _frame(T_VERIFY, struct.pack("<II", req_id, n) + body)
        if not all(len(d) == 32 for d in digests):
            # The service's fixed wire format carries 32-byte digests
            # (every deployed call site signs blake2b-256); anything else
            # is a test exotica — verify locally on the CPU oracle.
            return None
        body = b"".join(
            pk + digest + sig
            for pk, digest, sig in zip(public_keys, digests, signatures)
        )
        return _frame(T_RAW, struct.pack("<II", req_id, n) + body)

    # -- SignatureVerifier surface --

    def warmup(self) -> None:
        """Connect + HELLO: returns once the service's runtime is warm."""
        self._conn()

    def verify_signatures_async(self, public_keys, digests, signatures):
        """Staged dispatch: send the request now (on a pooled connection the
        handle carries — submit and fetch may run on different executor
        threads) and read the reply at ``result()``.  With the service's own
        per-connection request pipeline, several of these overlap through
        ONE warmed backend.  A send failure here falls back to the deferred
        sync path, which owns the full reconnect-retry budget."""
        n = len(signatures)
        if n == 0:
            return CompletedDispatch([])
        req_id = next(self._async_req_ids)
        frame = self._build_frame(
            public_keys, digests, signatures, req_id, n
        )
        if frame is None:
            return DeferredDispatch(
                CpuSignatureVerifier().verify_signatures,
                public_keys, digests, signatures,
            )
        try:
            conn = self._pool_checkout()
        except VerifierProtocolError:
            raise
        except (ConnectionError, OSError, socket.timeout):
            # No reconnect count here: the deferred sync fallback runs the
            # full retry loop and accounts each torn-down attempt itself.
            conn = None
        if conn is None:
            # Pool exhausted or unreachable: the sync path (thread-local
            # connection, bounded retries) carries the batch at fetch time.
            return DeferredDispatch(
                self.verify_signatures, public_keys, digests, signatures
            )
        try:
            conn.sendall(frame)
        except (ConnectionError, OSError, socket.timeout):
            self._pool_discard(conn)
            if self.metrics is not None:
                self.metrics.verifier_reconnect_total.inc()
            return DeferredDispatch(
                self.verify_signatures, public_keys, digests, signatures
            )
        return _RemoteDispatch(
            self, conn, req_id, n, public_keys, digests, signatures
        )

    def verify_signatures(self, public_keys, digests, signatures) -> List[bool]:
        n = len(signatures)
        if n == 0:
            return []
        self._tls.req_id = req_id = getattr(self._tls, "req_id", 0) + 1
        frame = self._build_frame(
            public_keys, digests, signatures, req_id, n
        )
        if frame is None:
            return CpuSignatureVerifier().verify_signatures(
                public_keys, digests, signatures
            )
        oks = self._roundtrip(frame, req_id)
        assert len(oks) == n
        return [bool(b) for b in oks]


class _RemoteDispatch:
    """An in-flight request to the verifier service.

    ``result()`` reads the reply off the handle's own connection and returns
    it to the pool.  A connection failure at fetch time is NOT fatal to the
    batch: the connection is discarded and the whole request re-runs through
    the sync path's bounded reconnect-retry budget (the service may have
    restarted mid-flight; re-verifying is idempotent)."""

    __slots__ = ("_client", "_conn", "_req_id", "_n", "_args")

    def __init__(self, client, conn, req_id, n, public_keys, digests,
                 signatures) -> None:
        self._client = client
        self._conn = conn
        self._req_id = req_id
        self._n = n
        self._args = (public_keys, digests, signatures)

    def result(self) -> List[bool]:
        client = self._client
        try:
            type_, payload = client._read_frame(self._conn)
        except VerifierProtocolError:
            client._pool_discard(self._conn)
            raise
        except (ConnectionError, OSError, socket.timeout):
            client._pool_discard(self._conn)
            if client.metrics is not None:
                client.metrics.verifier_reconnect_total.inc()
            return client.verify_signatures(*self._args)
        if type_ == T_ERR:
            client._pool_discard(self._conn)
            raise VerifierProtocolError(
                f"verifier service error: {payload.decode(errors='replace')}"
            )
        client._pool_checkin(self._conn)
        assert type_ == T_RESULT
        (echoed,) = struct.unpack_from("<I", payload)
        assert echoed == self._req_id, "verifier service response out of order"
        oks = payload[4:]
        assert len(oks) == self._n
        return [bool(b) for b in oks]

    def abandon(self) -> None:
        """Release without fetching (the flush was cancelled): a connection
        with an unread response must never return to the pool — the next
        request on it would read a stale frame — so it is discarded, which
        also keeps the pool's live-connection count honest."""
        self._client._pool_discard(self._conn)


def run_service(socket_path: str, committee_keys: Optional[Sequence[bytes]] = None,
                metrics_port: Optional[int] = None) -> None:
    """Blocking entry point for the CLI subcommand.  With ``metrics_port``
    the service also exposes /metrics + /healthz (queue depth, per-connection
    in-flight, dispatch batch sizes, padding waste)."""

    async def _main() -> None:
        metrics = None
        if metrics_port:
            from .metrics import Metrics, serve_metrics

            metrics = Metrics()
            await serve_metrics(metrics, "0.0.0.0", metrics_port)
        server = VerifierServer(
            socket_path, committee_keys=committee_keys, metrics=metrics
        )
        await server.serve_forever()

    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        pass
