# lint: ignore-module[sim-taint] — standalone socket-server process: runs
# outside any validator's event loop (real or simulated); its calibration
# clocks can never leak into a seeded sim's timeline.
"""Shared per-host verifier service: ONE warmed JAX runtime for the fleet.

Round-4 finding: giving every validator process its own JAX runtime
(``validator.py:_make_verifier``) made the TPU path lose to CPU at fleet
level — N processes serially paying import + PJRT init + trace/compile on a
shared host, then N independent connections to the accelerator.  The
reference never hits this because its verifier is a CPU function in-process
(``mysticeti-core/src/crypto.rs:174-189``); a TPU-first design wants the
opposite split: the accelerator runtime is a HOST resource, owned by one
process, shared by every co-located validator.

  * :class:`VerifierServer` — owns a single :class:`TpuSignatureVerifier`
    (one PJRT client, one compile cache, warmed once), serves signature
    batches over a unix-domain socket.  Requests from different validators
    dispatch concurrently (async device dispatch overlaps their round-trips).
  * :class:`RemoteSignatureVerifier` — the validator-side
    :class:`SignatureVerifier` that forwards batches to the service.  It
    never imports jax: a validator process using it boots import-light, and
    a REBOOTED validator re-attaches to the still-warm service instead of
    re-paying a cold runtime (the round-4 catch-up gap: 100 s+ of re-warm).

Wire protocol (little-endian, length-prefixed frames):

  frame    = u32 payload_len | u8 type | payload
  HELLO    (1)   u16 n_keys | n_keys * 32 B pk      -> HELLO_OK once warm
  VERIFY   (2)   u32 req_id | u32 n | n * (u16 key_idx | 32 B digest | 64 B sig)
  RAW      (3)   u32 req_id | u32 n | n * (32 B pk | 32 B digest | 64 B sig)
  HELLO_OK (128) f64 fixed_dispatch_s | f64 per_sig_s | utf-8 backend
                 (empty = uncalibrated; exactly 16 B = calibrated pre-r6
                 service, backend unknown)
  RESULT   (129) u32 req_id | n * u8 ok
  ERR      (255) utf-8 message (protocol error; connection closes)

The HELLO_OK ``backend`` suffix advertises the service's ACTUAL resolved
platform ("cpu" when no accelerator is attached or jax degraded to the host,
"tpu"/"tpu-pallas" when a chip answered) — the hybrid router pins routing to
its in-process oracle when the advertised backend is CPU-only, so the whole
socket hop disappears exactly when there is nothing behind it to pay for.
Version skew is safe in both directions: an old client sees a >16-byte
HELLO_OK, fails its ``len == 16`` calibration check, and falls back to its
own probe dispatch (it never parses the suffix); a new client against an old
service sees exactly 16 bytes and simply leaves the backend unknown (no
pinning — the conservative default).

HELLO doubles as the warmup gate: the reply is sent only after the backend's
one-time trace/compile finished, so a client's ``warmup()`` is "send HELLO,
wait" — seconds against a warm service, never minutes.  All clients must
present the same committee (one table per service); a mismatch is an ERR.

HELLO_OK carries the service's OWN dispatch calibration (a timed 1-signature
and batch dispatch after warmup): the hybrid router needs (fixed, per-sig)
cost estimates, and N validators each probing a shared-host service would
serialize N probe dispatches behind fleet boot contention — measured on a
1-core host, 5 of 7 validators were still waiting for their probe a minute
in.  One server-side measurement, taken once on an idle backend, is both
cheaper and more accurate.
"""
from __future__ import annotations

import asyncio
import itertools
import os
import random
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from .block_validator import (
    CpuSignatureVerifier,
    SignatureVerifier,
    VerifierProtocolError,
)
from .network import jittered_backoff
from .verify_pipeline import CompletedDispatch, DeferredDispatch
from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)

T_HELLO = 1
T_VERIFY = 2
T_RAW = 3
T_HELLO_OK = 128
T_RESULT = 129
T_ERR = 255

_IDX_REC = 2 + 32 + 64  # u16 idx | digest | sig
_RAW_REC = 32 + 32 + 64

ENV_SOCKET = "MYSTICETI_VERIFIER_SOCKET"

# VerifierProtocolError (re-exported above from block_validator): the service
# answered but REJECTED the request.  Excluded from the client's retry loop
# AND from the hybrid circuit breaker — a misconfigured validator fails fast
# instead of hammering the service or silently degrading to the oracle.


def _frame(type_: int, payload: bytes) -> bytes:
    """Small-frame builder (HELLO, HELLO_OK, ERR).  The hot paths — VERIFY
    requests client-side, RESULT replies service-side — do NOT come through
    here: they pack into reusable buffers / scatter-gather parts so payload
    bytes are copied at most once per direction (see ``_WireBuffer`` and
    ``VerifierServer._reply_writer``)."""
    return struct.pack("<IB", len(payload), type_) + payload


class _WireBuffer:
    """Reusable pack/recv scratch buffer: grown geometrically, never shrunk
    or reallocated per dispatch, so steady-state requests write into (and
    replies land in) the same allocation every time.  One per (thread,
    direction) on the client — the executor threads that pack and fetch own
    their connections thread-locally, so per-thread IS per-connection."""

    __slots__ = ("buf", "grows")

    def __init__(self, size: int = 4096) -> None:
        self.buf = bytearray(size)
        self.grows = 0

    def reserve(self, n: int) -> bytearray:
        if len(self.buf) < n:
            size = len(self.buf)
            while size < n:
                size *= 2
            self.buf = bytearray(size)
            self.grows += 1
        return self.buf


def _peer_uid(sock) -> Optional[int]:
    """UID of the unix-socket peer via SO_PEERCRED, or None when the
    platform cannot say (non-Linux): directory permissions remain the
    defense there.  Module-level so tests can stub a foreign peer."""
    if sock is None:
        return None
    try:
        creds = sock.getsockopt(
            socket.SOL_SOCKET, socket.SO_PEERCRED, struct.calcsize("3i")
        )
        _pid, uid, _gid = struct.unpack("3i", creds)
        return uid
    except (AttributeError, OSError, struct.error):
        return None


def _abandoned_reply(fut: asyncio.Future, cleanup) -> None:
    """Completion hook for a dispatch whose connection died before its reply
    could be written: retrieve the exception (so asyncio never logs it as
    never-retrieved at GC) and only then release the service gauges."""
    if not fut.cancelled() and fut.exception() is not None:
        log.error(
            "verifier service dispatch failed after client disconnect",
            exc_info=fut.exception(),
        )
    if cleanup is not None:
        cleanup()


# ---------------------------------------------------------------------------
# Server


class VerifierServer:
    """One accelerator runtime serving every validator on the host."""

    # Per-connection staged request window: the reader decodes request N+1
    # while N computes in the pool; replies are written strictly in request
    # order by a dedicated writer task.  The bound backpressures a client
    # pipelining faster than the backend drains.
    PIPELINE_DEPTH = 8

    def __init__(self, socket_path: str, committee_keys: Optional[Sequence[bytes]] = None,
                 backend=None, metrics=None) -> None:
        self.socket_path = socket_path
        self._backend = backend
        self._owns_backend = backend is None
        self._keys: Optional[List[bytes]] = (
            list(committee_keys) if committee_keys else None
        )
        # Optional Metrics: queue depth / per-connection in-flight gauges +
        # dispatch shape series, scrapeable when the service CLI runs with
        # --metrics-port (the fleet's verify queue was invisible before).
        self.metrics = metrics
        self._conn_ids = itertools.count()
        self._warmed = threading.Event()
        self._warm_lock = threading.Lock()
        # Sized for a 10+ validator fleet: each in-flight request blocks a
        # worker thread on the device fetch, and overlapping those
        # round-trips is the entire point of sharing the runtime.
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="verify-dispatch"
        )
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set = set()
        self._calibration: Optional[Tuple[float, float]] = None

    # -- backend lifecycle --

    def _ensure_backend(self, keys: List[bytes]):
        # The whole init+warmup runs under the lock: concurrent HELLOs from a
        # booting fleet must not race two warmups through the JAX tracer —
        # the losers just block here until the first one finishes (which is
        # exactly the contract their HELLO wants anyway).
        with self._warm_lock:
            if keys:
                if self._keys is None:
                    # First NON-EMPTY committee establishes the service key
                    # set (ADVICE r5: an early zero-key HELLO from a RAW-only
                    # client must not pin the committee to [] and poison
                    # every later client with a permanent mismatch).  If a
                    # keyless backend was already built for such a client,
                    # rebuild it around the real committee's key table.
                    self._keys = keys
                    if self._backend is not None and self._owns_backend:
                        self._backend = None
                        self._warmed.clear()
                elif self._keys != keys:
                    raise ValueError(
                        "committee mismatch: this verifier service was warmed "
                        "for a different key set"
                    )
            if self._backend is None:
                from .block_validator import TpuSignatureVerifier

                self._backend = TpuSignatureVerifier(committee_keys=self._keys)
                self._owns_backend = True
            if not self._warmed.is_set():
                self._backend.warmup()
                self._calibrate()
                self._warmed.set()
            return self._backend

    def _calibrate(self) -> None:
        """Time the warmed backend once: a 1-signature dispatch (fixed cost)
        and a 256-signature dispatch (marginal cost), on the deployed
        committee-indexed path.  Shared with every client via HELLO_OK."""
        import time

        keys = self._keys or []
        if not keys:
            return
        pk = keys[0]
        digest = bytes(32)
        sig = bytes(64)
        try:
            t0 = time.monotonic()
            self._backend.verify_signatures([pk], [digest], [sig])
            fixed = time.monotonic() - t0
            n = 256
            t0 = time.monotonic()
            self._backend.verify_signatures(
                [keys[i % len(keys)] for i in range(n)],
                [digest] * n, [sig] * n,
            )
            batch_t = time.monotonic() - t0
            self._calibration = (fixed, max(0.0, (batch_t - fixed) / n))
            log.info(
                "verifier service calibrated: %.1f ms fixed + %.1f µs/sig",
                1e3 * self._calibration[0], 1e6 * self._calibration[1],
            )
        except Exception:  # calibration is advisory, never fatal
            log.exception("verifier service calibration failed")

    def prewarm(self) -> None:
        """Warm before the first client connects (committee known at boot)."""
        if self._keys is None:
            raise ValueError("prewarm requires committee keys")
        self._ensure_backend(self._keys)

    # -- connection handling --

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # Trust gate first (VERDICT r5 #5): the socket lives in a 0700 dir,
        # but an unrelated local user who still reached it (shared parent
        # mount, pre-hardening dir) must not get to submit RAW batches to
        # the warmed backend.  Same-uid and root peers only.
        uid = _peer_uid(writer.get_extra_info("socket"))
        if uid is not None and uid not in (os.getuid(), 0):
            log.warning(
                "verifier service refusing foreign-uid peer (uid %d)", uid
            )
            writer.close()
            return
        # Staged per-connection request pipeline: the reader decodes and
        # submits request N+1 while request N computes in the pool; a
        # dedicated writer task emits replies strictly in request order (the
        # protocol contract clients rely on), so the service is no longer a
        # stop-and-wait RPC for a client that pipelines its frames.
        loop = asyncio.get_running_loop()
        self._writers.add(writer)
        conn_label = f"c{next(self._conn_ids)}"
        replies: asyncio.Queue = asyncio.Queue(maxsize=self.PIPELINE_DEPTH)
        reply_task = spawn_logged(
            self._reply_writer(replies, writer), log, name="verifier-replies"
        )

        def _accounted():
            metrics = self.metrics
            if metrics is None:
                return None
            # Depth = requests handed to the pool and not yet answered
            # (queued behind the 16 workers or mid-dispatch); inflight
            # splits it per client connection so one flooding validator is
            # attributable.  Decremented by the writer once the reply is
            # built (cleanup runs even when the dispatch raised).
            metrics.verifier_service_queue_depth.inc()
            metrics.verifier_service_inflight.labels(conn_label).inc()

            def _done():
                metrics.verifier_service_queue_depth.dec()
                metrics.verifier_service_inflight.labels(conn_label).dec()

            return _done

        # A pipelined client may send VERIFY frames behind a HELLO without
        # waiting for HELLO_OK; pool threads run jobs in any order, so a
        # verify must not EXECUTE before the HELLO that establishes the
        # committee finished (it would see no keys and report every slot
        # invalid).  Replies stay ordered by the queue; execution is gated
        # on the connection's last unresolved HELLO only.
        last_hello: Optional[asyncio.Future] = None

        async def _after_hello(gate, type_, req_id, n, body):
            try:
                hello_frame = await asyncio.shield(gate)
            except Exception:  # noqa: BLE001 - HELLO's own reply carries it
                hello_frame = None
            if hello_frame is None or hello_frame[4] == T_ERR:
                # The HELLO was rejected (committee mismatch) or crashed:
                # the connection is being severed and this reply would be
                # discarded in drain mode — do NOT burn a backend dispatch
                # for it (a reconnect-looping misconfigured client would
                # otherwise cost a device round-trip per queued frame).
                return None
            return await loop.run_in_executor(
                self._pool, self._result_reply, type_, req_id, n, body
            )

        try:
            while True:
                try:
                    header = await reader.readexactly(5)
                except asyncio.IncompleteReadError:
                    return
                if reply_task.done():
                    return  # writer died (client gone, backend crash)
                length, type_ = struct.unpack("<IB", header)
                payload = await reader.readexactly(length) if length else b""
                if type_ == T_HELLO:
                    n_keys = (
                        struct.unpack_from("<H", payload)[0]
                        if length >= 2 else -1
                    )
                    if n_keys < 0 or length != 2 + 32 * n_keys:
                        await replies.put(
                            (_frame(T_ERR, b"malformed hello frame"),
                             None, True)
                        )
                        return
                    keys = [
                        bytes(payload[2 + 32 * i: 2 + 32 * (i + 1)])
                        for i in range(n_keys)
                    ]
                    # HELLO replies ride the same in-order queue as results:
                    # a client that pipelines frames must never see HELLO_OK
                    # overtake an earlier RESULT.
                    fut = loop.run_in_executor(
                        self._pool, self._hello_reply, keys
                    )
                    last_hello = fut
                    await replies.put((fut, None, False))
                elif type_ in (T_VERIFY, T_RAW):
                    if length < 8:
                        await replies.put(
                            (_frame(T_ERR, b"malformed verify frame"),
                             None, True)
                        )
                        return
                    req_id, n = struct.unpack_from("<II", payload)
                    # memoryview, not a bytes slice: the request body is the
                    # bulk of every frame, and the per-record digest/sig
                    # slices below stay views too — the payload bytes the
                    # reader produced are the LAST host copy before the
                    # backend packs them device-ward.
                    body = memoryview(payload)[8:]
                    rec = _IDX_REC if type_ == T_VERIFY else _RAW_REC
                    if len(body) != n * rec:
                        await replies.put(
                            (_frame(T_ERR, b"malformed verify frame"),
                             None, True)
                        )
                        return
                    if last_hello is not None and last_hello.done():
                        rejected = last_hello.cancelled() or (
                            last_hello.exception() is not None
                            or last_hello.result()[4] == T_ERR
                        )
                        if rejected:
                            # The writer is severing after the HELLO's ERR:
                            # frames pipelined behind it must not burn
                            # backend dispatches for replies that will be
                            # discarded in drain mode.
                            return
                        last_hello = None  # accepted: no more gating needed
                    done = _accounted()
                    if last_hello is not None:
                        # Awaited by the reply writer in order, which
                        # observes its exception.
                        fut = asyncio.ensure_future(
                            _after_hello(last_hello, type_, req_id, n, body)
                        )
                    else:
                        fut = loop.run_in_executor(
                            self._pool, self._result_reply,
                            type_, req_id, n, body,
                        )
                    await replies.put((fut, done, False))
                else:
                    await replies.put(
                        (_frame(T_ERR, b"unknown frame type"), None, True)
                    )
                    return
        except (ConnectionResetError, BrokenPipeError, OSError):
            return
        finally:
            # Let the writer drain everything already submitted, then stop.
            try:
                replies.put_nowait(None)
            except asyncio.QueueFull:
                reply_task.cancel()
            try:
                await reply_task
            except asyncio.CancelledError:
                reply_task.cancel()
            except Exception:  # noqa: BLE001 - writer logged its own failure
                pass
            # Anything left unqueued-for-write still owes its cleanup, but
            # its dispatch may still be running on a pool thread: releasing
            # the gauges now would show an idle service during real device
            # work, and abandoning the future would leave its exception
            # unretrieved.  Defer both to the dispatch's own completion.
            abandoned = []
            while True:
                try:
                    item = replies.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if item is None:
                    continue
                frame, cleanup, _close_after = item
                if asyncio.isfuture(frame):
                    abandoned.append((frame, cleanup))
                elif cleanup is not None:
                    cleanup()

            def _remove_label() -> None:
                # Labels are minted per connection from an unbounded counter;
                # a reconnecting fleet would otherwise grow dead
                # {connection="cN"} series in the registry forever.
                if self.metrics is not None:
                    try:
                        self.metrics.verifier_service_inflight.remove(
                            conn_label
                        )
                    except KeyError:
                        pass  # connection closed before its first verify

            if abandoned:
                # The label must outlive every deferred cleanup: a dec()
                # after remove() would re-mint the dead series at -1 and
                # leak it forever.  The LAST abandoned dispatch to complete
                # removes it (done-callbacks run on the loop thread, so the
                # countdown needs no lock).
                remaining = {"n": len(abandoned)}

                def _finish(fut, cleanup) -> None:
                    _abandoned_reply(fut, cleanup)
                    remaining["n"] -= 1
                    if remaining["n"] == 0:
                        _remove_label()

                for fut, cleanup in abandoned:
                    fut.add_done_callback(
                        lambda f, cleanup=cleanup: _finish(f, cleanup)
                    )
            else:
                _remove_label()
            self._writers.discard(writer)
            writer.close()

    async def _reply_writer(self, replies: asyncio.Queue,
                            writer: asyncio.StreamWriter) -> None:
        """Emit queued replies in request order; ``None`` ends the stream.
        Queue items are ``(frame_or_future, cleanup, close_after)``.  A
        dispatch failure or a dead client socket flips to drain mode —
        remaining cleanups still run (gauge hygiene) but nothing is written,
        and the transport is closed so the reader unblocks.

        A reply is either a prebuilt ``bytes`` frame (HELLO_OK, ERR) or a
        ``(type, parts)`` tuple from the verify path: a fresh 5-byte header
        rides ``writer.writelines`` with the parts as-is — scatter-gather,
        no header+payload concatenation per reply.  The header must be a
        fresh immutable object per reply: since 3.12 the selector transport
        may hold a zero-copy view of writelines' buffers under
        backpressure, so a reused mutable scratch could be rewritten while
        frame N still sits unsent in the transport buffer."""
        dead = False
        while True:
            item = await replies.get()
            if item is None:
                return
            frame, cleanup, close_after = item
            try:
                if asyncio.isfuture(frame):
                    try:
                        frame = await frame
                    except Exception:  # noqa: BLE001 - logged, conn severed
                        log.exception("verifier service dispatch failed")
                        frame = None
                if dead or frame is None:
                    dead = True
                    writer.close()
                    continue
                if isinstance(frame, tuple):
                    type_, parts = frame
                else:
                    type_, parts = frame[4], None
                if type_ == T_ERR:
                    # Protocol errors sever the connection after the reply
                    # (the pre-pipeline contract), wherever they were built.
                    close_after = True
                try:
                    if parts is not None:
                        header = struct.pack(
                            "<IB", sum(len(p) for p in parts), type_
                        )
                        writer.writelines((header, *parts))
                    else:
                        writer.write(frame)
                    await writer.drain()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    dead = True
                    continue
                if close_after:
                    dead = True
                    writer.close()
            finally:
                if cleanup is not None:
                    cleanup()

    def _resolved_backend(self) -> str:
        """The platform the warmed backend ACTUALLY dispatches on —
        advertised to every client via HELLO_OK so their hybrid routers can
        short-circuit a service with no accelerator behind it.  Backends
        without the introspection hook are host oracles: "cpu"."""
        resolve = getattr(self._backend, "resolved_backend", None)
        if resolve is None:
            return "cpu"
        try:
            return str(resolve())
        except Exception:  # advisory, never fatal
            log.exception("backend platform introspection failed")
            return "cpu"

    def _hello_reply(self, keys: List[bytes]) -> bytes:
        """Pool-side HELLO handling: warm (or adopt/upgrade) the backend and
        frame the reply — HELLO_OK with the calibration + resolved-backend
        advertisement, or ERR on a committee mismatch (which also severs the
        connection client-side).  The backend suffix rides only behind a
        calibration: old clients check ``len == 16`` and fall back to their
        own probe, and an UNcalibrated reply stays the old empty payload so
        it is never mistaken for a 16-byte calibration."""
        try:
            self._ensure_backend(keys)
        except ValueError as exc:
            return _frame(T_ERR, str(exc).encode())
        payload = b""
        if self._calibration is not None:
            payload = struct.pack("<dd", *self._calibration)
            payload += self._resolved_backend().encode("ascii", "replace")
        return _frame(T_HELLO_OK, payload)

    def _result_reply(self, type_: int, req_id: int, n: int, body) -> tuple:
        """Verify and return the reply as ``(T_RESULT, parts)`` — the writer
        packs the frame header into its per-connection scratch and
        scatter-gathers the parts, so the verdicts are copied exactly once
        (list -> bytes) on their way out."""
        oks = self._verify_payload(type_, n, body)
        return (T_RESULT, (struct.pack("<I", req_id), bytes(oks)))

    def _verify_payload(self, type_: int, n: int, body: bytes) -> List[int]:
        backend = self._ensure_backend(self._keys or [])
        pks, digests, sigs = [], [], []
        if type_ == T_VERIFY:
            keys = self._keys or []
            for i in range(n):
                off = i * _IDX_REC
                (idx,) = struct.unpack_from("<H", body, off)
                if idx >= len(keys):
                    # An out-of-range index cannot verify; reject that slot
                    # rather than the whole batch.
                    pks.append(bytes(32))
                else:
                    pks.append(keys[idx])
                digests.append(body[off + 2: off + 34])
                sigs.append(body[off + 34: off + 98])
        else:
            for i in range(n):
                off = i * _RAW_REC
                pks.append(body[off: off + 32])
                digests.append(body[off + 32: off + 64])
                sigs.append(body[off + 64: off + 128])
        oks = backend.verify_signatures(pks, digests, sigs)
        if self.metrics is not None:
            # The service owns the device, so it (not the jax-free clients)
            # is where dispatch shape and padding waste are measurable.
            self.metrics.verify_dispatch_batch_size.observe(n)
            padder = getattr(backend, "padded_batch", None)
            if padder is not None:
                self.metrics.verify_padding_wasted_total.labels(
                    "service"
                ).inc(max(0, padder(n) - n))
        return [1 if ok else 0 for ok in oks]

    # -- lifecycle --

    @staticmethod
    def _secure_socket_dir(socket_path: str) -> None:
        """Bind-time trust check (VERDICT r5 #5), mirroring the jax
        compilation cache's discipline (ops/ed25519.py): the socket's parent
        directory must be OURS — created 0700 when absent, refused outright
        when another uid owns it (a foreign owner can rename/replace the
        socket under us), and stripped of group/other bits when we own a
        looser one.  SO_PEERCRED at accept covers the remaining window."""
        parent = os.path.dirname(os.path.abspath(socket_path)) or "."
        if not os.path.isdir(parent):
            os.makedirs(parent, mode=0o700, exist_ok=True)
        st = os.stat(parent)
        if st.st_uid != os.getuid():
            raise PermissionError(
                f"verifier socket dir {parent!r} is owned by uid {st.st_uid}"
                f" (we are {os.getuid()}): refusing to bind into a directory"
                " another user controls"
            )
        if st.st_mode & 0o077:
            os.chmod(parent, 0o700)

    async def start(self) -> None:
        self._secure_socket_dir(self.socket_path)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._server = await asyncio.start_unix_server(
            self._handle, path=self.socket_path
        )
        # Belt to the dir's braces: same-uid-or-root only, and the peercred
        # gate enforces it even where a path somehow stays reachable.
        os.chmod(self.socket_path, 0o600)
        log.info("verifier service listening on %s", self.socket_path)

    async def serve_forever(self) -> None:
        await self.start()
        if self._keys is not None and not self._warmed.is_set():
            # Warm while validators boot: their HELLOs block until done.
            await asyncio.get_running_loop().run_in_executor(
                self._pool, self.prewarm
            )
            log.info("verifier service warmed (%d committee keys)",
                     len(self._keys))
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Sever live client connections first: since 3.12,
            # ``wait_closed`` waits for every connection HANDLER to finish,
            # and handlers block in readexactly on idle-but-open clients.
            for writer in list(self._writers):
                writer.close()
            await self._server.wait_closed()
        self._pool.shutdown(wait=False)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)


# ---------------------------------------------------------------------------
# Client


class RemoteSignatureVerifier(SignatureVerifier):
    """Validator-side stub: forwards batches to the host's verifier service.

    jax-free by design — the validator process stays import-light and leans
    on the service's single warmed runtime.  Called from the batching
    collector's executor threads: each thread keeps its own connection
    (``threading.local``) so concurrent flushes pipeline through the service
    rather than serializing on one socket.
    """

    backend_label = "tpu-remote"

    # Reconnect-retry budget per request: a service restart mid-burst is
    # routine (seconds of downtime), a fleet boot race is routine — neither
    # is an outage.  Only exhausting the budget propagates, and the hybrid
    # circuit breaker takes it from there.
    MAX_ATTEMPTS = 4
    RETRY_BASE_BACKOFF_S = 0.05
    RETRY_MAX_BACKOFF_S = 1.0

    # Bound on idle pooled connections for the async dispatch path; matches
    # the deepest pipeline window the collector runs (verify_pipeline.py).
    MAX_POOLED_CONNS = 4

    def __init__(self, socket_path: Optional[str] = None,
                 committee_keys: Optional[Sequence[bytes]] = None,
                 timeout_s: float = 300.0,
                 metrics=None,
                 max_attempts: Optional[int] = None) -> None:
        self.socket_path = socket_path or os.environ[ENV_SOCKET]
        self._keys = list(committee_keys or [])
        self._index = {pk: i for i, pk in enumerate(self._keys)}
        self.timeout_s = timeout_s
        self.metrics = metrics
        self.max_attempts = max_attempts or self.MAX_ATTEMPTS
        self._retry_rng = random.Random(0x5E7C1E27)
        self._tls = threading.local()
        # Connection pool for the STAGED path (verify_signatures_async): the
        # submit and the fetch may run on different executor threads, so the
        # in-flight handle carries its connection instead of leaning on the
        # thread-local one.  _pool_size counts live pooled conns (idle +
        # checked out) so the pool stays bounded across threads.
        self._pool_conns: List[socket.socket] = []
        self._pool_lock = threading.Lock()
        self._pool_size = 0
        self._async_req_ids = itertools.count(1)
        # (fixed_dispatch_s, per_sig_s) as measured by the SERVICE on its
        # own warmed backend (HELLO_OK payload); None until first connect.
        self.calibration: Optional[Tuple[float, float]] = None
        # The service's resolved platform from the HELLO_OK backend suffix
        # ("cpu" | "tpu" | ...); None against a pre-r6 service or before the
        # first connect.  The hybrid router reads this to pin routing to its
        # in-process oracle when there is no accelerator behind the socket.
        self.advertised_backend: Optional[str] = None

    # -- socket plumbing --

    def _connect(self) -> socket.socket:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(self.timeout_s)
        conn.connect(self.socket_path)
        payload = struct.pack("<H", len(self._keys)) + b"".join(self._keys)
        frame = _frame(T_HELLO, payload)
        conn.sendall(frame)
        self._count_wire("sent", len(frame))
        type_, reply = self._read_frame(conn)
        if type_ != T_HELLO_OK:
            conn.close()
            raise VerifierProtocolError(
                "verifier service rejected hello: "
                f"{bytes(reply).decode(errors='replace')}"
            )
        if len(reply) >= 16:
            self.calibration = struct.unpack_from("<dd", reply)
        # No suffix (pre-r6 service, or uncalibrated) = backend UNKNOWN —
        # overwrite, don't keep: a stale "cpu" from a replaced service
        # would otherwise hold the hybrid pinned against hardware whose
        # platform nobody actually advertised.
        self.advertised_backend = (
            bytes(reply[16:]).decode("ascii", errors="replace")
            if len(reply) > 16
            else None
        )
        return conn

    def dispatch_calibration(self) -> Optional[Tuple[float, float]]:
        """Server-measured (fixed_s, per_sig_s) — the hybrid router's cost
        model, without every client paying its own probe dispatch."""
        return self.calibration

    def rehello(self) -> Tuple[Optional[str], Optional[Tuple[float, float]]]:
        """Fresh HELLO round-trip on this thread's connection; returns the
        service's CURRENT (advertised_backend, calibration).

        This is the backend-pinned hybrid router's low-frequency upgrade
        probe: one HELLO frame over the wire, never a batch — a service that
        gained an accelerator (chip window opened, tunnel healed, service
        restarted on real hardware) re-opens offload without a validator
        restart.  Transport failures propagate for the caller's backoff."""
        stale = getattr(self._tls, "conn", None)
        self._tls.conn = None
        if stale is not None:
            try:
                stale.close()
            except OSError:
                pass
        self._conn()
        return self.advertised_backend, self.calibration

    def _conn(self) -> socket.socket:
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = self._connect()
            self._tls.conn = conn
            self._tls.req_id = 0
        return conn

    def _count_wire(self, direction: str, nbytes: int) -> None:
        if self.metrics is not None:
            self.metrics.verify_wire_bytes_total.labels(direction).inc(nbytes)

    def _wire(self, attr: str) -> _WireBuffer:
        """Per-thread reusable buffer, one per direction: ``pack`` must stay
        intact across the retry loop's reconnects (which read HELLO_OK into
        ``recv``), and each thread owns its connections so per-thread is
        per-connection."""
        wire = getattr(self._tls, attr, None)
        if wire is None:
            wire = _WireBuffer()
            setattr(self._tls, attr, wire)
        return wire

    @staticmethod
    def _recv_exact(conn: socket.socket, view: memoryview) -> None:
        got, n = 0, len(view)
        while got < n:
            r = conn.recv_into(view[got:])
            if r == 0:
                raise ConnectionError("verifier service closed the connection")
            got += r

    def _read_frame(self, conn: socket.socket):
        """Read one frame into the per-thread recv buffer: the payload lands
        via ``recv_into`` (one kernel→buffer move, no per-chunk bytes
        concatenation) and is returned as a memoryview.  The view aliases
        the reusable buffer — callers consume it before this thread's next
        read, which every call site does (verdict bytes become a list, ERR
        text becomes a string, calibration floats are unpacked)."""
        wire = self._wire("recv")
        head = memoryview(wire.reserve(5))[:5]
        self._recv_exact(conn, head)
        length, type_ = struct.unpack_from("<IB", head)
        payload = memoryview(wire.reserve(length))[:length]
        if length:
            self._recv_exact(conn, payload)
        self._count_wire("recv", 5 + length)
        return type_, payload

    def _roundtrip(self, frame, req_id: int):
        """Send one request with bounded reconnect-retries.

        The round-5 reconnect-ONCE policy made a service restart during a
        fleet burst a fatal outage: every in-flight thread burned its single
        retry against the not-yet-listening socket and propagated.  Retries
        are bounded (``max_attempts``) with jittered exponential backoff so
        a thundering herd of dispatch threads does not hammer the recovering
        service in lockstep; each torn-down connection counts on
        ``verifier_reconnect_total``.  Protocol rejections
        (:class:`VerifierProtocolError`) are never retried, and exhausting
        the budget propagates — the hybrid circuit breaker takes it from
        there."""
        backoff = self.RETRY_BASE_BACKOFF_S
        for attempt in range(self.max_attempts):
            try:
                conn = self._conn()
                conn.sendall(frame)
                self._count_wire("sent", len(frame))
                type_, payload = self._read_frame(conn)
                break
            except VerifierProtocolError:
                raise
            except (ConnectionError, OSError, socket.timeout):
                stale = getattr(self._tls, "conn", None)
                self._tls.conn = None
                if stale is not None:
                    try:
                        stale.close()
                    except OSError:
                        pass
                if self.metrics is not None:
                    self.metrics.verifier_reconnect_total.inc()
                if attempt + 1 >= self.max_attempts:
                    raise
                time.sleep(jittered_backoff(backoff, self._retry_rng))
                backoff = min(backoff * 2.0, self.RETRY_MAX_BACKOFF_S)
        if type_ == T_ERR:
            raise VerifierProtocolError(
                "verifier service error: "
                f"{bytes(payload).decode(errors='replace')}"
            )
        assert type_ == T_RESULT
        (echoed,) = struct.unpack_from("<I", payload)
        assert echoed == req_id, "verifier service response out of order"
        return payload[4:]

    # -- connection pool (async dispatch path) --

    def _pool_checkout(self) -> Optional[socket.socket]:
        """An idle pooled connection, a fresh one, or None when the pool is
        at its live-connection cap (idle + checked out) — the caller then
        falls back to the sync path's thread-local connection."""
        with self._pool_lock:
            if self._pool_conns:
                return self._pool_conns.pop()
            if self._pool_size >= self.MAX_POOLED_CONNS:
                return None
            self._pool_size += 1
        try:
            return self._connect()
        except BaseException:
            with self._pool_lock:
                self._pool_size -= 1
            raise

    def _pool_checkin(self, conn: socket.socket) -> None:
        with self._pool_lock:
            if len(self._pool_conns) < self.MAX_POOLED_CONNS:
                self._pool_conns.append(conn)
                return
            self._pool_size -= 1
        try:
            conn.close()
        except OSError:
            pass

    def _pool_discard(self, conn: socket.socket) -> None:
        with self._pool_lock:
            self._pool_size -= 1
        try:
            conn.close()
        except OSError:
            pass

    # -- frame building --

    def _pack_request(self, public_keys, digests, signatures, req_id, n):
        """Frame one request directly into this thread's reusable wire
        buffer and return a memoryview of it, or None when the batch cannot
        ride the service wire format (non-digest messages -> local oracle).

        This is the zero-copy half of the request direction: each digest /
        signature / key is slice-assigned into the buffer exactly ONCE, the
        header and per-record indices are packed in place, and the socket
        sends straight from the buffer — no ``b"".join`` body, no
        header+payload concatenation, no per-dispatch allocation once the
        buffer has grown to the steady-state batch size."""
        if not all(len(d) == 32 for d in digests):
            # The service's fixed wire format carries 32-byte digests
            # (every deployed call site signs blake2b-256); anything else
            # is a test exotica — verify locally on the CPU oracle.
            return None
        indices = [self._index.get(pk) for pk in public_keys]
        indexed = all(i is not None for i in indices)
        rec = _IDX_REC if indexed else _RAW_REC
        total = 5 + 8 + n * rec
        buf = self._wire("pack").reserve(total)
        struct.pack_into(
            "<IBII", buf, 0,
            total - 5, T_VERIFY if indexed else T_RAW, req_id, n,
        )
        off = 13
        if indexed:
            for idx, digest, sig in zip(indices, digests, signatures):
                struct.pack_into("<H", buf, off, idx)
                buf[off + 2:off + 34] = digest
                buf[off + 34:off + 98] = sig
                off += _IDX_REC
        else:
            for pk, digest, sig in zip(public_keys, digests, signatures):
                buf[off:off + 32] = pk
                buf[off + 32:off + 64] = digest
                buf[off + 64:off + 128] = sig
                off += _RAW_REC
        return memoryview(buf)[:total]

    # -- SignatureVerifier surface --

    def warmup(self) -> None:
        """Connect + HELLO: returns once the service's runtime is warm."""
        self._conn()

    def verify_signatures_async(self, public_keys, digests, signatures):
        """Staged dispatch: send the request now (on a pooled connection the
        handle carries — submit and fetch may run on different executor
        threads) and read the reply at ``result()``.  With the service's own
        per-connection request pipeline, several of these overlap through
        ONE warmed backend.  A send failure here falls back to the deferred
        sync path, which owns the full reconnect-retry budget."""
        n = len(signatures)
        if n == 0:
            return CompletedDispatch([])
        req_id = next(self._async_req_ids)
        frame = self._pack_request(
            public_keys, digests, signatures, req_id, n
        )
        if frame is None:
            return DeferredDispatch(
                CpuSignatureVerifier().verify_signatures,
                public_keys, digests, signatures,
            )
        try:
            conn = self._pool_checkout()
        except VerifierProtocolError:
            raise
        except (ConnectionError, OSError, socket.timeout):
            # No reconnect count here: the deferred sync fallback runs the
            # full retry loop and accounts each torn-down attempt itself.
            conn = None
        if conn is None:
            # Pool exhausted or unreachable: the sync path (thread-local
            # connection, bounded retries) carries the batch at fetch time.
            return DeferredDispatch(
                self.verify_signatures, public_keys, digests, signatures
            )
        try:
            conn.sendall(frame)
            self._count_wire("sent", len(frame))
        except (ConnectionError, OSError, socket.timeout):
            self._pool_discard(conn)
            if self.metrics is not None:
                self.metrics.verifier_reconnect_total.inc()
            return DeferredDispatch(
                self.verify_signatures, public_keys, digests, signatures
            )
        return _RemoteDispatch(
            self, conn, req_id, n, public_keys, digests, signatures
        )

    def verify_signatures(self, public_keys, digests, signatures) -> List[bool]:
        n = len(signatures)
        if n == 0:
            return []
        self._tls.req_id = req_id = getattr(self._tls, "req_id", 0) + 1
        frame = self._pack_request(
            public_keys, digests, signatures, req_id, n
        )
        if frame is None:
            return CpuSignatureVerifier().verify_signatures(
                public_keys, digests, signatures
            )
        oks = self._roundtrip(frame, req_id)
        assert len(oks) == n
        return [bool(b) for b in oks]


class _RemoteDispatch:
    """An in-flight request to the verifier service.

    ``result()`` reads the reply off the handle's own connection and returns
    it to the pool.  A connection failure at fetch time is NOT fatal to the
    batch: the connection is discarded and the whole request re-runs through
    the sync path's bounded reconnect-retry budget (the service may have
    restarted mid-flight; re-verifying is idempotent)."""

    __slots__ = ("_client", "_conn", "_req_id", "_n", "_args")

    def __init__(self, client, conn, req_id, n, public_keys, digests,
                 signatures) -> None:
        self._client = client
        self._conn = conn
        self._req_id = req_id
        self._n = n
        self._args = (public_keys, digests, signatures)

    def result(self) -> List[bool]:
        client = self._client
        try:
            type_, payload = client._read_frame(self._conn)
        except VerifierProtocolError:
            client._pool_discard(self._conn)
            raise
        except (ConnectionError, OSError, socket.timeout):
            client._pool_discard(self._conn)
            if client.metrics is not None:
                client.metrics.verifier_reconnect_total.inc()
            return client.verify_signatures(*self._args)
        if type_ == T_ERR:
            client._pool_discard(self._conn)
            raise VerifierProtocolError(
                "verifier service error: "
                f"{bytes(payload).decode(errors='replace')}"
            )
        client._pool_checkin(self._conn)
        assert type_ == T_RESULT
        (echoed,) = struct.unpack_from("<I", payload)
        assert echoed == self._req_id, "verifier service response out of order"
        oks = payload[4:]
        assert len(oks) == self._n
        return [bool(b) for b in oks]

    def abandon(self) -> None:
        """Release without fetching (the flush was cancelled): a connection
        with an unread response must never return to the pool — the next
        request on it would read a stale frame — so it is discarded, which
        also keeps the pool's live-connection count honest."""
        self._client._pool_discard(self._conn)


def run_service(socket_path: str, committee_keys: Optional[Sequence[bytes]] = None,
                metrics_port: Optional[int] = None) -> None:
    """Blocking entry point for the CLI subcommand.  With ``metrics_port``
    the service also exposes /metrics + /healthz (queue depth, per-connection
    in-flight, dispatch batch sizes, padding waste)."""

    async def _main() -> None:
        metrics = None
        if metrics_port:
            from .metrics import Metrics, serve_metrics

            metrics = Metrics()
            await serve_metrics(metrics, "0.0.0.0", metrics_port)
        server = VerifierServer(
            socket_path, committee_keys=committee_keys, metrics=metrics
        )
        await server.serve_forever()

    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        pass
