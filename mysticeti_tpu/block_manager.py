"""Causal-completeness gate: park blocks whose parents are missing, release on arrival.

Capability parity with ``mysticeti-core/src/block_manager.rs``:

* ``add_blocks`` (block_manager.rs:48-136) — accepts blocks whose whole causal
  history is stored, persisting them through the ``BlockWriter``; otherwise parks
  them in ``blocks_pending`` with reverse edges in ``block_references_waiting``.
  Returns (newly processed [(position, block)], first-seen missing references).
* ``missing_blocks`` (:138) — per-authority sets of references the synchronizer
  should fetch.
* ``exists_or_pending`` (:142-144).
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Sequence, Set, Tuple

from .block_store import BlockStore, BlockWriter
from .types import BlockReference, StatementBlock
from .wal import WalPosition


class BlockManager:
    def __init__(self, block_store: BlockStore, num_authorities: int, metrics=None) -> None:
        self.blocks_pending: Dict[BlockReference, StatementBlock] = {}
        self.block_references_waiting: Dict[BlockReference, Set[BlockReference]] = {}
        self.missing: List[Set[BlockReference]] = [set() for _ in range(num_authorities)]
        self.block_store = block_store
        self._metrics = metrics
        # Storage-GC floor (storage.py): includes strictly below it are
        # treated as satisfied — the blocks were retired from disk here
        # (and from well-behaved peers), so parking/fetching on them would
        # wait forever.  Raised by Core.cleanup and by snapshot adoption.
        self.gc_floor = 0

    def set_gc_floor(
        self, gc_floor: int, block_writer: BlockWriter
    ) -> Tuple[List[Tuple[WalPosition, StatementBlock]], Set[BlockReference]]:
        """Raise the floor, forget sub-floor missing refs, and re-evaluate
        every parked block against the new rule (a snapshot-streamed block
        whose parents sit below the adopted floor releases here).  Returns
        the same shape as :meth:`add_blocks` so the caller can ingest the
        released blocks through its normal path."""
        if gc_floor <= self.gc_floor:
            return [], set()
        self.gc_floor = gc_floor
        for refs in self.missing:
            stale = {r for r in refs if r.round < gc_floor}
            refs -= stale
        parked = list(self.blocks_pending.values())
        self.blocks_pending.clear()
        self.block_references_waiting.clear()
        if not parked:
            return [], set()
        return self.add_blocks(parked, block_writer)

    def add_blocks(
        self, blocks: Sequence[StatementBlock], block_writer: BlockWriter
    ) -> Tuple[List[Tuple[WalPosition, StatementBlock]], Set[BlockReference]]:
        # Ascending round order avoids spurious missing references when a batch
        # contains both parent and child (block_manager.rs:56-58).
        queue: Deque[StatementBlock] = deque(sorted(blocks, key=lambda b: b.round()))
        newly_processed: List[Tuple[WalPosition, StatementBlock]] = []
        missing_references: Set[BlockReference] = set()
        while queue:
            block = queue.popleft()
            reference = block.reference
            if reference.round < self.gc_floor:
                # Settled history: consensus has permanently moved past this
                # round and the store retired it.  Re-ingesting (a straggler
                # re-delivering an ancient block, a far-behind peer's stale
                # proposal) would re-vote and re-include blocks every healthy
                # aggregator already certified-and-retired — drop it.
                continue
            if self.block_store.block_exists(reference) or reference in self.blocks_pending:
                continue

            processed = True
            for include in block.includes:
                if include.round < self.gc_floor:
                    continue  # settled below the GC floor: never park on it
                if self.block_store.block_exists(include):
                    continue
                processed = False
                # Report an unseen parent only the first time anyone waits on it
                # and it is not itself parked here (block_manager.rs:80-88).
                if (
                    include not in self.block_references_waiting
                    and include not in self.blocks_pending
                ):
                    missing_references.add(include)
                self.block_references_waiting.setdefault(include, set()).add(reference)
                if include not in self.blocks_pending:
                    self.missing[include.authority].add(include)
            self.missing[reference.authority].discard(reference)

            if not processed:
                self.blocks_pending[reference] = block
                if self._metrics is not None:
                    self._metrics.blocks_suspended.inc()
                continue

            position = block_writer.insert_block(block)
            newly_processed.append((position, block))

            # Release any parked blocks that were waiting on this one and now
            # have all parents stored (block_manager.rs:112-131).
            waiting = self.block_references_waiting.pop(reference, None)
            if waiting:
                for waiting_ref in waiting:
                    parked = self.blocks_pending[waiting_ref]
                    if all(
                        inc not in self.block_references_waiting
                        for inc in parked.includes
                    ):
                        queue.appendleft(self.blocks_pending.pop(waiting_ref))

        return newly_processed, missing_references

    def missing_blocks(self) -> List[Set[BlockReference]]:
        return self.missing

    def exists_or_pending(self, reference: BlockReference) -> bool:
        # Sub-floor references read as settled so the dedup gate drops their
        # re-deliveries BEFORE paying signature verification.
        if reference.round < self.gc_floor:
            return True
        return self.block_store.block_exists(reference) or reference in self.blocks_pending
