"""Per-block span tracing: where did a committed block's latency go?

The end-to-end histograms in :mod:`metrics` say *how slow* commits are;
this module says *which stage* ate the time.  Every block is tracked through
the pipeline as a sequence of structured spans keyed by
``(stage, block reference, authority)``:

    receive        net_sync: frame decode + dedup + structure checks
    verify         net_sync: signature verification (collection window +
                   dispatch, through the pluggable verifier)
    verify_dispatch block_validator: one actual accelerator/CPU dispatch
                   (per block of the dispatched sub-batch)
    verify_pack    / verify_device / verify_fetch — the staged pipeline's
                   sub-stages of that dispatch (host packing, non-blocking
                   device submission, result fetch; verify_pipeline.py)
    dag_add        net_sync -> core: core-task queue wait + BlockManager
                   insertion (includes time parked on missing parents)
    proposal_wait  core -> commit_observer: accepted into the DAG until
                   sequenced by a committed sub-dag
    commit         syncer: leader decision + observer + commit persistence
    finalize       commit_observer: sub-dag linearization + tx accounting

Spans are clocked by the RUNTIME clock (:func:`mysticeti_tpu.runtime.now`):
virtual under :class:`~mysticeti_tpu.runtime.simulated.DeterministicLoop`
(so a seeded sim produces a byte-identical trace every run) and monotonic in
production.  Track identity reuses :data:`tracing.current_authority` as the
default, with explicit ``authority=`` at sites that know their validator
index — in a multi-node simulation all nodes share one process and one
tracer, and the authority keeps their pipelines on separate tracks.

Export is Chrome trace-event JSON, loadable in Perfetto / chrome://tracing:
set ``MYSTICETI_TRACE=/path/out.json`` (``%p`` expands to the pid, like
``MYSTICETI_PROFILE``) and the node CLI starts a tracer at boot and writes
the trace at shutdown.  A daemon thread flushes the file atomically every
few seconds so a SIGKILL'd benchmark node still leaves a complete snapshot
(same posture as ``profiling.SamplingProfiler``).  ``tools/trace_report.py``
prints per-stage latency breakdowns from a trace file.
"""
from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from .runtime import now as runtime_now
from .tracing import current_authority

# The central stage-name registry.  Instrumentation sites must use literal
# names from this tuple — the `span-names` lint rule in analysis/checker.py
# parses this assignment (it must stay a literal tuple of strings) and flags
# any span call whose stage is not registered.
STAGES = (
    # Fleet-trace stages (tools/fleet_trace.py): the author's proposal edge
    # (the journey's t=0) and the per-link wire transit measured from the
    # timestamped-frame extension (wire tag 12) — args carry the sending
    # peer and the RAW signed transit the skew estimator consumes.
    "propose",
    "transit",
    "receive",
    "verify",
    "verify_dispatch",
    # Staged dispatch pipeline sub-stages (verify_pipeline.py): host packing,
    # non-blocking device submission, and the result fetch — per dispatched
    # block, so a trace shows WHERE a dispatch's round-trip went.
    "verify_pack",
    "verify_device",
    "verify_fetch",
    "dag_add",
    "proposal_wait",
    "commit",
    "finalize",
)

# The per-block pipeline proper: the stages every committed block crosses.
# (verify_dispatch is per accelerator dispatch, absent under AcceptAll.)
PIPELINE_STAGES = (
    "receive",
    "verify",
    "dag_add",
    "proposal_wait",
    "commit",
    "finalize",
)

ENV_TRACE = "MYSTICETI_TRACE"

# tid for spans recorded with no authority context (e.g. tooling).
_UNTRACKED_TID = 1 << 20


def format_ref(ref) -> str:
    """Stable human-readable block-reference label for trace args."""
    return f"A{ref.authority}R{ref.round}#{ref.digest[:4].hex()}"


class SpanTracer:
    """Collects per-block stage spans; exports Chrome trace-event JSON.

    Thread-safe (the periodic flusher reads from a daemon thread while the
    event loop records), but all recording sites live on the loop thread, so
    under the deterministic simulator the event sequence — and therefore the
    exported bytes — is a pure function of the seed.
    """

    # Hard caps: a long-lived production node must not grow without bound.
    # proposal_wait spans of blocks that never commit are the main leak;
    # beyond the cap new records are dropped (counted, never raising).
    MAX_EVENTS = 1_000_000
    MAX_OPEN = 200_000

    def __init__(
        self,
        flush_path: Optional[str] = None,
        flush_every_s: float = 5.0,
    ) -> None:
        # Completed spans: (stage, ref label, authority, t0, t1, extra args).
        self._events: List[Tuple[str, str, Optional[int], float, float,
                                 Optional[dict]]] = []
        # Clock anchor for cross-node trace merging (tools/fleet_trace.py):
        # one (runtime, wall) pair captured at the FIRST recorded span, on
        # the recording thread — the merger converts each trace's runtime
        # timestamps to wall time through it.  Captured once (not per
        # flush) so a seeded sim's exported bytes stay a pure function of
        # the seed.
        self._anchor: Optional[Tuple[float, float]] = None
        # Open spans: (stage, ref, authority) -> t0.
        self._open: Dict[Tuple[str, object, Optional[int]], float] = {}
        # Live subscribers called with (stage, ref, authority, t0, t1) for
        # every COMPLETED span (the critical-path analyzer in health.py).
        # Called outside the lock, on the recording thread; sinks must be
        # cheap and never raise.
        self._sinks: List = []
        self._lock = threading.Lock()
        # Serializes write(): the periodic flusher thread and an orderly-
        # shutdown flush_active() both target the same <path>.tmp — unlocked,
        # one thread's os.replace could publish the file while the other is
        # still appending to the fd, interleaving two JSON documents.
        self._write_lock = threading.Lock()
        self.dropped = 0
        self.flush_path = flush_path
        self.flush_every_s = flush_every_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- clock --

    @staticmethod
    def now() -> float:
        """The runtime clock: virtual under simulation, monotonic otherwise."""
        return runtime_now()

    # -- live span stream --

    def add_sink(self, sink) -> None:
        """Subscribe to the completed-span stream: ``sink(stage, ref,
        authority, t0, t1)`` per recorded span, event-cap independent (a
        dropped trace event still feeds attribution)."""
        self._sinks.append(sink)

    def _notify(self, stage, ref, authority, t0, t1) -> None:
        for sink in self._sinks:
            sink(stage, ref, authority, t0, t1)

    # -- recording --

    def record_span(
        self,
        stage: str,
        ref,
        t0: float,
        t1: Optional[float] = None,
        authority: Optional[int] = None,
        extra: Optional[dict] = None,
    ) -> None:
        """Append a completed span measured by the caller.  ``extra`` lands
        in the exported event's ``args`` (next to the block label) — the
        ``transit`` stage uses it to carry the sending peer and the raw
        signed transit for the skew estimator."""
        if authority is None:
            authority = current_authority.get()
        if t1 is None:
            t1 = runtime_now()
        self._notify(stage, ref, authority, t0, t1)
        with self._lock:
            if self._anchor is None:
                from .runtime import timestamp_utc

                self._anchor = (runtime_now(), timestamp_utc())
            if len(self._events) >= self.MAX_EVENTS:
                self.dropped += 1
                return
            self._events.append(
                (stage, format_ref(ref), authority, t0, t1, extra)
            )

    def begin_span(
        self,
        stage: str,
        ref,
        authority: Optional[int] = None,
        t: Optional[float] = None,
    ) -> None:
        """Open a span; a later :meth:`end_span` with the same key closes it.
        A key already open keeps its ORIGINAL start (duplicate deliveries
        must not shrink the measured wait)."""
        if authority is None:
            authority = current_authority.get()
        if t is None:
            t = runtime_now()
        key = (stage, ref, authority)
        with self._lock:
            if key not in self._open:
                if len(self._open) >= self.MAX_OPEN:
                    self.dropped += 1
                    return
                self._open[key] = t

    def end_span(
        self,
        stage: str,
        ref,
        authority: Optional[int] = None,
        t: Optional[float] = None,
    ) -> None:
        """Close an open span; silently ignored when no matching begin was
        seen (e.g. a block that entered the DAG before tracing started)."""
        if authority is None:
            authority = current_authority.get()
        key = (stage, ref, authority)
        if t is None:
            t = runtime_now()
        with self._lock:
            t0 = self._open.pop(key, None)
            if t0 is None:
                return
            if self._anchor is None:
                from .runtime import timestamp_utc

                self._anchor = (runtime_now(), timestamp_utc())
            if len(self._events) >= self.MAX_EVENTS:
                self.dropped += 1
            else:
                self._events.append(
                    (stage, format_ref(ref), authority, t0, t, None)
                )
        self._notify(stage, ref, authority, t0, t)

    @contextmanager
    def span(self, stage: str, ref, authority: Optional[int] = None):
        t0 = runtime_now()
        try:
            yield
        finally:
            self.record_span(stage, ref, t0, authority=authority)

    # -- export --

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (Perfetto-loadable).

        One track ("thread") per authority, named ``A<n>``; spans are
        complete ("X") events with microsecond virtual/monotonic timestamps.
        Events are globally sorted on a total key so the output is
        deterministic (and per-track timestamps are monotone by
        construction).  Only COMPLETED spans are exported.
        """
        pid = os.getpid()
        with self._lock:
            events = list(self._events)
            anchor = self._anchor

        def tid_of(authority: Optional[int]) -> int:
            return _UNTRACKED_TID if authority is None else authority

        tids = {}
        for _, _, authority, _, _, _ in events:
            tid = tid_of(authority)
            tids[tid] = "untracked" if authority is None else f"A{authority}"
        trace_events = [
            {
                "args": {"name": "mysticeti-tpu"},
                "name": "process_name",
                "ph": "M",
                "pid": pid,
            }
        ]
        for tid in sorted(tids):
            trace_events.append(
                {
                    "args": {"name": tids[tid]},
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                }
            )
        spans = [
            {
                "args": (
                    {"block": label}
                    if not extra
                    else {"block": label, **extra}
                ),
                "cat": "pipeline",
                "dur": max(0, round((t1 - t0) * 1e6)),
                "name": stage,
                "ph": "X",
                "pid": pid,
                "tid": tid_of(authority),
                "ts": round(t0 * 1e6),
            }
            for stage, label, authority, t0, t1, extra in events
        ]
        spans.sort(key=lambda e: (e["ts"], e["tid"], e["name"], e["args"]["block"], e["dur"]))
        trace_events.extend(spans)
        trace = {"displayTimeUnit": "ms", "traceEvents": trace_events}
        if anchor is not None:
            # Cross-node merge anchor (tools/fleet_trace.py): the same
            # instant on the trace's runtime clock and the wall clock,
            # microseconds.  Virtual-deterministic under the simulator.
            trace["otherData"] = {
                "clock_runtime_us": round(anchor[0] * 1e6),
                "clock_wall_us": round(anchor[1] * 1e6),
            }
        return trace

    def write(self, path: str) -> None:
        """Atomic write (tmp + rename): a SIGKILL landing mid-flush must not
        replace the previous complete snapshot with a truncated file.
        Thread-safe: the flusher thread and shutdown flushes share the tmp."""
        tmp = f"{path}.tmp"
        with self._write_lock:
            with open(tmp, "w") as f:
                json.dump(
                    self.chrome_trace(), f, sort_keys=True,
                    separators=(",", ":"),
                )
                f.write("\n")
            os.replace(tmp, path)

    # -- periodic flush (survive SIGKILL, like profiling.SamplingProfiler) --

    def start(self) -> "SpanTracer":
        if self.flush_path and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run_flusher, name="mysticeti-tracer", daemon=True
            )
            self._thread.start()
        return self

    def _run_flusher(self) -> None:
        while not self._stop.wait(self.flush_every_s):
            try:
                self.write(self.flush_path)
            except OSError:
                pass

    def stop(self) -> None:
        """Stop the flusher and write the final complete trace."""
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None
        if self.flush_path:
            self.write(self.flush_path)


# ---------------------------------------------------------------------------
# Process-global tracer (instrumentation sites read it on their hot path)

_active: Optional[SpanTracer] = None


def active() -> Optional[SpanTracer]:
    """The live tracer, or None when tracing is off (the common case: one
    global read and a None check per instrumentation site)."""
    return _active


def start_from_env() -> Optional[SpanTracer]:
    """Start trace collection when ``MYSTICETI_TRACE`` is set; the node CLI
    calls this at boot and :func:`stop_from_env` at shutdown.  ``%p`` in the
    path expands to the pid so one env var serves a whole local fleet."""
    global _active
    path = os.environ.get(ENV_TRACE)
    if not path or _active is not None:
        return None
    path = path.replace("%p", str(os.getpid()))
    _active = SpanTracer(flush_path=path).start()
    return _active


def flush_active() -> None:
    """Write the live tracer's current snapshot NOW (orderly-shutdown hook:
    ``Validator.stop`` calls this so short runs keep the span tail instead
    of losing everything since the last periodic flush).  The tracer stays
    active — stop_from_env still finalizes it."""
    tracer = _active
    if tracer is None or not tracer.flush_path:
        return
    try:
        tracer.write(tracer.flush_path)
    except OSError:
        pass


def stop_from_env() -> None:
    """Write the final trace and deactivate the global tracer."""
    global _active
    if _active is None:
        return
    _active.stop()
    _active = None


# ---------------------------------------------------------------------------
# Shared trace loading + stage extraction (tools/trace_report.py AND
# tools/fleet_trace.py).  One implementation on purpose: the two consumers
# used to carry their own copies of the salvage/extraction logic, and a
# trace tail truncated mid-flush could land on different stage boundaries in
# each — the critical-path report and the fleet merge then disagreed about
# the same file.


def salvage_trace_events(text: str) -> List[dict]:
    """Recover complete event objects from a truncated trace: find the
    traceEvents array and raw-decode objects one at a time until the tear."""
    start = text.find('"traceEvents"')
    if start < 0:
        return []
    start = text.find("[", start)
    if start < 0:
        return []
    decoder = json.JSONDecoder()
    events: List[dict] = []
    pos = start + 1
    n = len(text)
    while pos < n:
        while pos < n and text[pos] in " \t\r\n,":
            pos += 1
        if pos >= n or text[pos] == "]":
            break
        try:
            event, pos = decoder.raw_decode(text, pos)
        except ValueError:
            break  # the tear: everything before it is intact
        if isinstance(event, dict):
            events.append(event)
    return events


def _salvage_other_data(text: str) -> dict:
    """The clock anchor survives most tears (sort_keys puts ``otherData``
    before ``traceEvents`` in our own exports); best-effort recover it."""
    start = text.find('"otherData"')
    if start < 0:
        return {}
    start = text.find("{", start + len('"otherData"'))
    if start < 0:
        return {}
    try:
        other, _ = json.JSONDecoder().raw_decode(text, start)
    except ValueError:
        return {}
    return other if isinstance(other, dict) else {}


def load_trace_events(path: str):
    """All events from a Chrome trace-event JSON file.

    Returns ``(events, note, other_data)``: a truncated/mid-flush tail is
    tolerated by salvaging the complete events before the tear (reported
    through ``note``); ``other_data`` carries the clock anchor when present.
    """
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except ValueError:
        events = salvage_trace_events(text)
        note = (
            f"note: trace is truncated (mid-flush tail?); salvaged "
            f"{len(events)} complete event(s)"
        )
        return events, note, _salvage_other_data(text)
    if isinstance(data, dict):
        events = data.get("traceEvents")
        if not isinstance(events, list):
            return [], "note: no traceEvents array in trace", {}
        return events, "", data.get("otherData") or {}
    if isinstance(data, list):
        return data, "", {}
    return [], "note: unrecognized trace shape", {}


def complete_spans(events: List[dict]) -> List[dict]:
    """Complete ("X") span events."""
    return [e for e in events if e.get("ph") == "X"]


def track_names(events: List[dict]) -> Dict[Tuple[int, int], str]:
    """(pid, tid) -> track name from the thread_name metadata events."""
    return {
        (e.get("pid", 0), e.get("tid", 0)): e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }


def stage_chains(
    span_events: List[dict], stages: Optional[Tuple[str, ...]] = None
) -> Dict[Tuple[Tuple[int, int], str], Dict[str, Tuple[int, int]]]:
    """Per-block stage chains: ``(track=(pid, tid), block label) ->
    {stage: (first ts µs, max dur µs)}``.

    The ONE extraction rule both offline consumers share: duplicate spans
    for the same (track, block, stage) — retransmits, flush overlap —
    keep the EARLIEST start and the LONGEST duration.  ``stages`` filters
    which span names participate (default: every registered stage).
    """
    allowed = set(stages if stages is not None else STAGES)
    chains: Dict[Tuple[Tuple[int, int], str], Dict[str, Tuple[int, int]]] = {}
    for e in span_events:
        name = e.get("name")
        if name not in allowed:
            continue
        label = (e.get("args") or {}).get("block")
        if not label:
            continue
        track = (e.get("pid", 0), e.get("tid", 0))
        ts = e.get("ts", 0)
        dur = e.get("dur", 0)
        entry = chains.setdefault((track, label), {})
        prev = entry.get(name)
        if prev is None:
            entry[name] = (ts, dur)
        else:
            entry[name] = (min(prev[0], ts), max(prev[1], dur))
    return chains
