"""Committee: stake table, thresholds, leader election, vote aggregation.

Capability parity with ``mysticeti-core/src/committee.rs``:

* ``Committee`` with validity (>1/3) and quorum (>2/3) stake thresholds
  (committee.rs:25-30,56-81) and genesis block construction (committee.rs:98-114).
* Deterministic stake-weighted leader election (committee.rs:149-180) — our own
  blake2b-PRF weighted sampling without replacement; CONSENSUS-CRITICAL: every
  validator must compute the identical leader, so the scheme below is part of the
  protocol definition, not an implementation detail.
* ``StakeAggregator`` over quorum/validity thresholds (committee.rs:256-330).
* ``TransactionAggregator`` — the per-transaction fast-path vote/certification
  engine over locator ranges (committee.rs:363-482), backed by ``RangeMap``.
* ``VoteRangeBuilder`` — run-length compression of accept votes (committee.rs:498-524).
"""
from __future__ import annotations

import hashlib
import struct
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import crypto
from .range_map import RangeMap
from .serde import Reader, Writer
from .types import (
    AuthorityIndex,
    AuthoritySet,
    BlockReference,
    Epoch,
    MAX_COMMITTEE_SIZE,
    Share,
    StatementBlock,
    TransactionLocator,
    TransactionLocatorRange,
    Vote,
    VoteRange,
)

Stake = int

QUORUM = "quorum"
VALIDITY = "validity"

ROUND_ROBIN = "round_robin"
STAKE_WEIGHTED = "stake_weighted"


class Authority:
    """One committee member: stake + verifying key + hostname (committee.rs:197-218)."""

    __slots__ = ("stake", "public_key", "hostname")

    def __init__(self, stake: Stake, public_key: crypto.PublicKey, hostname: str = "") -> None:
        self.stake = stake
        self.public_key = public_key
        self.hostname = hostname


class Committee:
    """The validator set for one epoch (committee.rs:24-30).

    ``leader_election`` selects round-robin (the reference's cfg(test) strategy,
    committee.rs:140-146 — used by the committer gold suite) or the production
    stake-weighted scheme.
    """

    def __init__(
        self,
        authorities: Sequence[Authority],
        epoch: Epoch = 0,
        leader_election: str = STAKE_WEIGHTED,
        epoch_tolerant: bool = False,
    ) -> None:
        if not authorities:
            raise ValueError("committee must not be empty")
        if len(authorities) > MAX_COMMITTEE_SIZE:
            raise ValueError(f"committee larger than {MAX_COMMITTEE_SIZE}")
        if any(a.stake < 0 for a in authorities):
            raise ValueError("stakes must be non-negative")
        # Stable-index membership (reconfig.py): stake 0 marks a registered
        # but INACTIVE authority — it keeps its index, key, and genesis block
        # but contributes nothing to thresholds and is unelectable.  At
        # least one member must be active or no quorum exists at all.
        if all(a.stake == 0 for a in authorities):
            raise ValueError("at least one authority must have positive stake")
        self.authorities: Tuple[Authority, ...] = tuple(authorities)
        self.epoch = epoch
        self.leader_election = leader_election
        # Epoch-tolerant committees accept blocks stamped with OTHER epoch
        # numbers (reconfiguration: honest peers straddle a boundary for a
        # few rounds, and a rejoiner catches up through older epochs' blocks).
        # Signatures still bind blocks to this registry's keys, so tolerance
        # never admits another deployment's blocks.
        self.epoch_tolerant = epoch_tolerant
        self.total_stake: Stake = sum(a.stake for a in authorities)
        # is_valid: amount > total/3 ; is_quorum: amount > 2*total/3 (committee.rs:56-57,120-127)
        self._validity_floor = self.total_stake // 3
        self._quorum_floor = 2 * self.total_stake // 3

    # -- constructors --

    @classmethod
    def new_test(cls, stakes: Sequence[Stake], epoch: Epoch = 0) -> "Committee":
        """Test committee with dummy keys + round-robin election (committee.rs:36-39)."""
        dummy = crypto.Signer.dummy().public_key
        return cls(
            [Authority(s, dummy) for s in stakes], epoch, leader_election=ROUND_ROBIN
        )

    @classmethod
    def new_for_benchmarks(
        cls,
        size: int,
        epoch: Epoch = 0,
        stakes: Optional[Sequence[Stake]] = None,
    ) -> "Committee":
        """Equal-stake committee with deterministic per-index keys
        (committee.rs:190-193).  ``stakes`` overrides the per-index stakes
        (churn scenarios register a joiner at stake 0)."""
        if stakes is not None and len(stakes) != size:
            raise ValueError("stakes must have one entry per authority")
        return cls(
            [
                Authority(1 if stakes is None else stakes[i], s.public_key)
                for i, s in enumerate(cls.benchmark_signers(size))
            ],
            epoch,
            leader_election=STAKE_WEIGHTED,
        )

    def with_stakes(
        self, stakes: Sequence[Stake], epoch: Epoch
    ) -> "Committee":
        """Derive another epoch's committee over the SAME registry: keys,
        hostnames, and election strategy carry over; only stakes and the
        epoch number change.  Derived committees are epoch-tolerant (their
        whole point is to live through a boundary)."""
        if len(stakes) != len(self.authorities):
            raise ValueError("stakes must have one entry per authority")
        return Committee(
            [
                Authority(stake, a.public_key, a.hostname)
                for stake, a in zip(stakes, self.authorities)
            ],
            epoch,
            leader_election=self.leader_election,
            epoch_tolerant=True,
        )

    @staticmethod
    def benchmark_signers(size: int) -> List[crypto.Signer]:
        return [crypto.Signer.from_seed(i.to_bytes(32, "little")) for i in range(size)]

    # -- YAML round-trip (committee.rs:34 committee.yaml via Print trait) --

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "leader_election": self.leader_election,
            "authorities": [
                {
                    "stake": a.stake,
                    "public_key": a.public_key.bytes.hex(),
                    "hostname": a.hostname,
                }
                for a in self.authorities
            ],
        }

    @classmethod
    def from_dict(cls, raw: dict) -> "Committee":
        return cls(
            [
                Authority(
                    a["stake"],
                    crypto.PublicKey(bytes.fromhex(a["public_key"])),
                    a.get("hostname", ""),
                )
                for a in raw["authorities"]
            ],
            epoch=raw.get("epoch", 0),
            leader_election=raw.get("leader_election", STAKE_WEIGHTED),
        )

    def dump(self, path: str) -> None:
        import yaml

        with open(path, "w") as f:
            yaml.safe_dump(self.to_dict(), f, sort_keys=False)

    @classmethod
    def load(cls, path: str) -> "Committee":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    # -- thresholds --

    def validity_threshold(self) -> Stake:
        return self._validity_floor + 1

    def quorum_threshold(self) -> Stake:
        return self._quorum_floor + 1

    def is_valid(self, amount: Stake) -> bool:
        return amount > self._validity_floor

    def is_quorum(self, amount: Stake) -> bool:
        return amount > self._quorum_floor

    def threshold_predicate(self, kind: str) -> Callable[[Stake], bool]:
        if kind == QUORUM:
            return self.is_quorum
        if kind == VALIDITY:
            return self.is_valid
        raise ValueError(f"unknown threshold kind {kind}")

    # -- lookups --

    def __len__(self) -> int:
        return len(self.authorities)

    def known_authority(self, authority: AuthorityIndex) -> bool:
        return 0 <= authority < len(self.authorities)

    def accepts_epoch(self, epoch: Epoch) -> bool:
        """Block-verification epoch gate (types.verify_structure): exact
        match by default; epoch-tolerant committees (reconfiguration) accept
        any epoch number — keys are stable across stake changes, so the
        signature check still rejects foreign blocks."""
        return epoch == self.epoch or self.epoch_tolerant

    def is_active(self, authority: AuthorityIndex) -> bool:
        """Positive stake == active member of this epoch (stable-index
        membership: stake 0 marks a registered-but-retired/not-yet-joined
        authority)."""
        return (
            self.known_authority(authority)
            and self.authorities[authority].stake > 0
        )

    def active_authorities(self) -> List[AuthorityIndex]:
        return [i for i, a in enumerate(self.authorities) if a.stake > 0]

    def active_count(self) -> int:
        return sum(1 for a in self.authorities if a.stake > 0)

    def get_stake(self, authority: AuthorityIndex) -> Stake:
        return self.authorities[authority].stake

    def get_public_key(self, authority: AuthorityIndex) -> crypto.PublicKey:
        return self.authorities[authority].public_key

    def public_key_bytes(self) -> List[bytes]:
        """Every authority's raw 32-byte key, in index order — the committee
        table the TPU verifier and the verifier service key on."""
        return [a.public_key.bytes for a in self.authorities]

    def authority_indexes(self) -> range:
        return range(len(self.authorities))

    def get_total_stake(self, authorities: Iterable[AuthorityIndex]) -> Stake:
        return sum(self.authorities[a].stake for a in authorities)

    # -- genesis (committee.rs:98-114) --

    def genesis_blocks(self, for_authority: AuthorityIndex):
        own = StatementBlock.new_genesis(for_authority, self.epoch)
        others = [
            StatementBlock.new_genesis(a, self.epoch)
            for a in self.authority_indexes()
            if a != for_authority
        ]
        return own, others

    # -- leader election --

    def elect_leader(self, round_: int, offset: int = 0) -> AuthorityIndex:
        """Leader for (round, offset) (committee.rs:137-146)."""
        if self.leader_election == ROUND_ROBIN:
            return (round_ + offset) % len(self.authorities)
        return self.elect_leader_stake_based(round_, offset)

    def elect_leader_stake_based(self, round_: int, offset: int) -> AuthorityIndex:
        """Deterministic stake-weighted election without replacement
        (semantics of committee.rs:149-180; our own PRF, documented protocol rule):

        draws 0..=offset each pick one authority with probability proportional to
        stake among those not yet drawn, using ``blake2b(b"leader" || round || draw)``
        as the randomness.  Distinct offsets in the same round therefore always yield
        distinct leaders.
        """
        if offset >= len(self.authorities):
            raise ValueError("offset must be < committee size")
        if round_ == 0:
            return 0
        remaining: List[Tuple[AuthorityIndex, Stake]] = [
            (i, a.stake) for i, a in enumerate(self.authorities)
        ]
        total = self.total_stake
        chosen = 0
        for draw in range(offset + 1):
            seed = hashlib.blake2b(
                b"mysticeti-tpu/leader"
                + round_.to_bytes(8, "little")
                + draw.to_bytes(8, "little"),
                digest_size=16,
            ).digest()
            point = int.from_bytes(seed, "little") % total
            acc = 0
            for j, (idx, stake) in enumerate(remaining):
                acc += stake
                if point < acc:
                    chosen = idx
                    total -= stake
                    remaining.pop(j)
                    break
        return chosen


class StakeAggregator:
    """Accumulates distinct authority votes until a stake threshold
    (committee.rs:256-330).  ``kind`` is "quorum" or "validity"."""

    __slots__ = ("kind", "votes", "stake")

    def __init__(self, kind: str = QUORUM) -> None:
        self.kind = kind
        self.votes = AuthoritySet()
        self.stake: Stake = 0

    def add(self, vote: AuthorityIndex, committee: Committee) -> bool:
        if self.votes.insert(vote):
            self.stake += committee.get_stake(vote)
        return committee.threshold_predicate(self.kind)(self.stake)

    def is_reached(self, committee: Committee) -> bool:
        return committee.threshold_predicate(self.kind)(self.stake)

    def clear(self) -> None:
        self.votes.clear()
        self.stake = 0

    def copy(self) -> "StakeAggregator":
        """Independent copy — required by RangeMap fragment splitting."""
        dup = StakeAggregator(self.kind)
        dup.votes = self.votes.copy()
        dup.stake = self.stake
        return dup

    def voters(self):
        return self.votes.present()

    # state snapshot encoding (for WAL persistence of aggregator state)
    def encode(self, w: Writer) -> None:
        w.u8(0 if self.kind == QUORUM else 1)
        w.u64(self.stake)
        w.bytes(self.votes.bits.to_bytes(64, "little"))

    @staticmethod
    def decode(r: Reader) -> "StakeAggregator":
        kind = QUORUM if r.u8() == 0 else VALIDITY
        agg = StakeAggregator(kind)
        agg.stake = r.u64()
        agg.votes = AuthoritySet(int.from_bytes(r.bytes(), "little"))
        return agg


class TransactionAggregator:
    """Fast-path vote/certification engine over transaction locator ranges
    (committee.rs:363-482).

    ``pending`` maps a sharing block's reference to a RangeMap of offset ranges →
    StakeAggregator.  When a range reaches the threshold it is removed and reported
    processed.  ``handler`` hooks mirror ProcessedTransactionHandler
    (committee.rs:297-312): by default a set of processed locators that panics on
    votes for unknown transactions and on duplicate shares (the reference's
    HashSet impl, committee.rs:314-330).
    """

    def __init__(self, kind: str = QUORUM, track_processed: bool = True) -> None:
        self.kind = kind
        self.pending: Dict[BlockReference, RangeMap] = {}
        self.track_processed = track_processed
        self.processed: Set[TransactionLocator] = set()
        # Set by with_state: the processed set is NOT part of the snapshot
        # (same as the reference, committee.rs:352-362), so after recovery
        # votes/shares for pre-snapshot transactions are EXPECTED, not
        # Byzantine — the duplicate/unknown oracles cannot assert what they
        # did not persist.  Leniency is scoped by round: only locators whose
        # sharing block's round is <= the recovery watermark (the highest
        # round the restored state could have known about) bypass the
        # oracles; anything first shared above the watermark is strictly
        # checked for the aggregator's whole remaining life.
        self.recovered = False
        self.recovered_watermark: Optional[int] = None
        # Native hot core (native/mysticeti_native.cpp VoteAggregator): the
        # per-offset Python objects (locator tuples, StakeAggregator
        # instances, set hashing) dominate the engine profile at load, so the
        # sweep/tally/processed-set state lives in C++ when the extension is
        # available.  Pure-Python `pending`/`processed` above are the
        # fallback; MYSTICETI_NO_NATIVE=1 pins it.
        from .native import native as _native

        self._nat = None
        self._nat_mod = _native
        if _native is not None and hasattr(_native, "va_new"):
            self._nat = _native.va_new(track_processed, 0 if kind == QUORUM else 1)
            self._refs: Dict[bytes, BlockReference] = {}
            self._nat_committee: Optional[Committee] = None

    @staticmethod
    def _key(block: BlockReference) -> bytes:
        return struct.pack("<QQ", block.authority, block.round) + block.digest

    def _nat_bind(self, committee: Committee) -> None:
        if self._nat_committee is not committee:
            threshold = (
                committee.quorum_threshold()
                if self.kind == QUORUM
                else committee.validity_threshold()
            )
            self._nat_mod.va_bind(
                self._nat,
                [committee.get_stake(a) for a in committee.authority_indexes()],
                threshold,
            )
            self._nat_committee = committee

    def _raise_violations(self, viol_ranges, block, vote, hook) -> None:
        """Feed native violation ranges through the overridable handler hook
        offset-by-offset, deferring exceptions to the end — exact parity with
        the pure path's sweep (every violating offset observes the hook; the
        first collected exception is raised after the map update completed)."""
        violations: List[Exception] = []
        for s, e in viol_ranges:
            for off in range(s, e):
                try:
                    hook(TransactionLocator(block, off), vote)
                except Exception as exc:  # noqa: BLE001 - deferred, re-raised
                    violations.append(exc)
        if violations:
            raise violations[0]

    # handler hooks — overridable by subclasses
    def transaction_processed(self, k: TransactionLocator) -> None:
        # The native core records certified intervals itself; the Python set
        # only backs the fallback path.
        if self.track_processed and self._nat is None:
            self.processed.add(k)

    def transaction_processed_range(
        self, block: "BlockReference", start: int, end: int
    ) -> None:
        """Range form of the processed hook: certification happens in
        contiguous runs (often thousands of offsets at default block caps),
        and building a locator object per offset was a top engine cost at
        fleet saturation.  Subclasses that only need per-offset semantics
        keep overriding ``transaction_processed``."""
        if (
            type(self).transaction_processed
            is TransactionAggregator.transaction_processed
            and (not self.track_processed or self._nat is not None)
        ):
            # Base hook would no-op per offset (the native core keeps its
            # own intervals): skip the per-offset loop entirely.  A subclass
            # override of the singular hook still sees every offset.
            return
        for off in range(start, end):
            self.transaction_processed(TransactionLocator(block, off))

    def _pre_snapshot(self, k: TransactionLocator) -> bool:
        """True when the locator may predate the recovered snapshot — the
        oracles cannot assert what the snapshot did not persist."""
        return (
            self.recovered
            and (
                self.recovered_watermark is None
                or k.block.round <= self.recovered_watermark
            )
        )

    def duplicate_transaction(self, k: TransactionLocator, from_: AuthorityIndex) -> None:
        if (
            self.track_processed
            and not self._pre_snapshot(k)
            and k not in self.processed
        ):
            raise RuntimeError(f"duplicate transaction {k} from {from_}")

    def unknown_transaction(self, k: TransactionLocator, from_: AuthorityIndex) -> None:
        if (
            self.track_processed
            and not self._pre_snapshot(k)
            and k not in self.processed
        ):
            raise RuntimeError(f"vote for unknown transaction {k} from {from_}")

    def is_processed(self, k: TransactionLocator) -> bool:
        if self._nat is not None:
            return self._nat_mod.va_is_processed(
                self._nat, self._key(k.block), k.offset
            )
        return k in self.processed

    # -- core operations (committee.rs:364-425) --

    def register(
        self,
        locator_range: TransactionLocatorRange,
        vote: AuthorityIndex,
        committee: Committee,
    ) -> None:
        """A block shared these transactions; start aggregation with the author's
        implicit self-vote.

        Handler violations (duplicate shares) are collected during the sweep and
        raised only after the RangeMap update completes — raising mid-sweep would
        leave ``pending`` partially mutated, and unlike the reference (which aborts
        the process on these panics) a Python caller may catch and continue, so the
        aggregator must stay internally consistent."""
        if self._nat is not None:
            block = locator_range.block
            key = self._key(block)
            self._refs[key] = block
            self._nat_bind(committee)
            viol_ranges = self._nat_mod.va_register(
                self._nat,
                key,
                locator_range.offset_start_inclusive,
                locator_range.offset_end_exclusive,
                vote,
            )
            self._raise_violations(
                viol_ranges, block, vote, self.duplicate_transaction
            )
            return
        range_map = self.pending.setdefault(locator_range.block, RangeMap())
        violations: List[Exception] = []

        def mutate(sub_start: int, sub_end: int, agg):
            if agg is not None:
                for off in range(sub_start, sub_end):
                    try:
                        self.duplicate_transaction(
                            TransactionLocator(locator_range.block, off), vote
                        )
                    except Exception as e:  # noqa: BLE001 - deferred, re-raised below
                        violations.append(e)
                return agg
            new_agg = StakeAggregator(self.kind)
            new_agg.add(vote, committee)
            return new_agg

        range_map.mutate_range(
            locator_range.offset_start_inclusive,
            locator_range.offset_end_exclusive,
            mutate,
        )
        if violations:
            raise violations[0]

    def vote(
        self,
        locator_range: TransactionLocatorRange,
        vote: AuthorityIndex,
        committee: Committee,
        processed_out: List[TransactionLocatorRange],
    ) -> None:
        """Tally a vote range; newly certified runs are appended to
        ``processed_out`` as RANGES (certification is contiguous — a range
        per certified run instead of a locator per offset keeps the
        default-cap fast path out of O(transactions) Python loops)."""
        if self._nat is not None:
            block = locator_range.block
            key = self._key(block)
            self._nat_bind(committee)
            certified, viol_ranges, retired = self._nat_mod.va_vote(
                self._nat,
                key,
                locator_range.offset_start_inclusive,
                locator_range.offset_end_exclusive,
                vote,
            )
            if retired:
                self._refs.pop(key, None)
            for s, e in certified:
                self.transaction_processed_range(block, s, e)
                processed_out.append(TransactionLocatorRange(block, s, e))
            self._raise_violations(
                viol_ranges, block, vote, self.unknown_transaction
            )
            return
        range_map = self.pending.get(locator_range.block)
        if range_map is None:
            for loc in locator_range.locators():
                self.unknown_transaction(loc, vote)
            return
        violations: List[Exception] = []

        def mutate(sub_start: int, sub_end: int, agg):
            if agg is None:
                # Deferred like register(): keep the sweep atomic wrt `pending`.
                for off in range(sub_start, sub_end):
                    try:
                        self.unknown_transaction(
                            TransactionLocator(locator_range.block, off), vote
                        )
                    except Exception as e:  # noqa: BLE001 - deferred, re-raised below
                        violations.append(e)
                return None
            if agg.add(vote, committee):
                self.transaction_processed_range(
                    locator_range.block, sub_start, sub_end
                )
                processed_out.append(
                    TransactionLocatorRange(
                        locator_range.block, sub_start, sub_end
                    )
                )
                return None  # certified: drop from pending
            return agg

        range_map.mutate_range(
            locator_range.offset_start_inclusive,
            locator_range.offset_end_exclusive,
            mutate,
        )
        if range_map.is_empty():
            del self.pending[locator_range.block]
        if violations:
            raise violations[0]

    def process_block(
        self,
        block: StatementBlock,
        response: Optional[List[object]],
        committee: Committee,
    ) -> List[TransactionLocatorRange]:
        """Tally one block's shares and votes (committee.rs:450-482).

        Shares register new aggregations (and, if ``response`` is given, emit our own
        VoteRange replies into it); Vote/VoteRange statements are tallied; returns
        the locator RANGES newly certified by this block.
        """
        processed: List[TransactionLocatorRange] = []
        for rng in shared_ranges(block):
            self.register(rng, block.author(), committee)
            if response is not None:
                response.append(VoteRange(rng))
        for st in block.statements:
            if isinstance(st, Vote):
                if st.accept:
                    self.vote(
                        TransactionLocatorRange(st.locator.block, st.locator.offset,
                                                st.locator.offset + 1),
                        block.author(), committee, processed,
                    )
                else:
                    raise NotImplementedError("reject votes not implemented (parity: committee.rs:470)")
            elif isinstance(st, VoteRange):
                self.vote(st.range, block.author(), committee, processed)
        return processed

    def __len__(self) -> int:
        if self._nat is not None:
            return self._nat_mod.va_pending_len(self._nat)
        return len(self.pending)

    def is_empty(self) -> bool:
        return len(self) == 0

    # -- state snapshot (committee.rs:352-362), our own encoding --

    def state(self) -> bytes:
        if self._nat is not None:
            if hasattr(self._nat_mod, "va_state"):
                # Snapshot serialized entirely in C++ — the per-commit state
                # write is the engine's top cost at deep pending backlogs
                # (O(pending) every commit); _nat_state below is the
                # byte-identical reference encoder it is differential-tested
                # against.
                return self._nat_mod.va_state(self._nat)
            return self._nat_state()
        w = Writer()
        w.u32(len(self.pending))
        for block_ref in sorted(self.pending):
            rm = self.pending[block_ref]
            block_ref.encode(w)
            w.u32(len(rm))
            for s, e, agg in rm.items():
                w.u64(s).u64(e)
                agg.encode(w)
        return w.finish()

    def _nat_state(self) -> bytes:
        # Byte-identical to the pure-Python encoder: the native sweep splits
        # ranges exactly like RangeMap.mutate_range, so the item lists match.
        items = self._nat_mod.va_items(self._nat)
        by_ref = sorted(
            (self._refs[key], ranges) for key, ranges in items
        )
        w = Writer()
        w.u32(len(by_ref))
        for block_ref, ranges in by_ref:
            block_ref.encode(w)
            w.u32(len(ranges))
            for s, e, stake, kind, mask in ranges:
                w.u64(s).u64(e)
                w.u8(kind).u64(stake)
                w.bytes(mask)
        return w.finish()

    def relax_below(self, watermark_round: int) -> None:
        """Snapshot catch-up (storage.py): the node adopted a remote commit
        baseline, so every block below the adopted floor is history it will
        NEVER process — votes and shares referencing that history are
        expected, not Byzantine.  Raises (never lowers) the pre-snapshot
        leniency watermark; locators first shared above it stay strictly
        checked, exactly as after a with_state recovery."""
        if not self.recovered:
            self.recovered = True
            self.recovered_watermark = watermark_round
        elif (
            self.recovered_watermark is not None
            and watermark_round > self.recovered_watermark
        ):
            # None means unbounded leniency (pure reference parity) — never
            # narrow it here.
            self.recovered_watermark = watermark_round

    def with_state(
        self, state: bytes, watermark_round: Optional[int] = None
    ) -> None:
        """Restore from a snapshot.  ``watermark_round`` bounds the Byzantine-
        oracle leniency (see ``_pre_snapshot``): the caller should pass the
        highest round durably replayed alongside the snapshot (e.g.
        ``BlockStore.highest_round()``) so locators first shared ABOVE it stay
        strictly checked.  When omitted the leniency is unbounded (pure
        reference-parity behavior): the snapshot alone cannot bound what was
        processed before it — completed transactions may sit at rounds above
        any still-pending entry — so no safe round bound is derivable."""
        if len(self):
            raise RuntimeError("with_state requires an empty aggregator")
        self.recovered = True
        self.recovered_watermark = watermark_round
        r = Reader(state)
        for _ in range(r.u32()):
            block_ref = BlockReference.decode(r)
            rm = RangeMap()
            n = r.u32()
            for _ in range(n):
                s, e = r.u64(), r.u64()
                if self._nat is not None:
                    kind = r.u8()
                    stake = r.u64()
                    mask = r.bytes()
                    key = self._key(block_ref)
                    self._refs[key] = block_ref
                    self._nat_mod.va_load(self._nat, key, s, e, stake, kind, mask)
                else:
                    agg = StakeAggregator.decode(r)
                    rm.mutate_range(s, e, lambda a, b, _old, agg=agg: agg)
            if self._nat is None:
                self.pending[block_ref] = rm
        r.expect_done()


def shared_ranges(block: StatementBlock) -> List[TransactionLocatorRange]:
    """Contiguous runs of Share statements in a block as locator ranges
    (types.rs shared_ranges equivalent used by committee.rs:455); run-length
    compression delegated to VoteRangeBuilder so there is one copy of that logic."""
    runs = getattr(block, "_share_runs", None)
    if runs is None:
        # Locally built block: walk the statements.  Wire-decoded blocks
        # carry spans precomputed by the native decoder — re-walking 10k+
        # statements per block here was a top interpreter cost at load.
        builder = VoteRangeBuilder()
        collected: List[Tuple[int, int]] = []
        for i, st in enumerate(block.statements):
            if isinstance(st, Share):
                done = builder.add(i)
                if done is not None:
                    collected.append(done)
        tail = builder.finish()
        if tail is not None:
            collected.append(tail)
        runs = collected
    return [TransactionLocatorRange(block.reference, s, e) for s, e in runs]


class VoteRangeBuilder:
    """Run-length compression of vote offsets (committee.rs:498-524)."""

    __slots__ = ("_start", "_end")

    def __init__(self) -> None:
        self._start: Optional[int] = None
        self._end = 0

    def add(self, offset: int) -> Optional[Tuple[int, int]]:
        """Feed the next offset; returns a completed (start, end) run when the new
        offset is not contiguous with the current run."""
        if self._start is None:
            self._start, self._end = offset, offset + 1
            return None
        if self._end == offset:
            self._end = offset + 1
            return None
        result = (self._start, self._end)
        self._start, self._end = offset, offset + 1
        return result

    def finish(self) -> Optional[Tuple[int, int]]:
        if self._start is None:
            return None
        return (self._start, self._end)
