"""Write-ahead log: append-only tagged entries with crc32 framing and mmap reads.

Capability parity with ``mysticeti-core/src/wal.rs``:

* ``walf(path) -> (WalWriter, WalReader)``                      (wal.rs:38-41)
* 16-byte entry header (magic, crc32, len, tag)                  (wal.rs:110-112,211-223)
* positional addressing: a ``WalPosition`` is the byte offset of the entry header,
  ``POSITION_MAX`` is the reserved "none" position                (wal.rs:31-36)
* reads return memory-mapped views                               (wal.rs:226-259)
* ``iter_until`` replay iterator used for crash recovery         (wal.rs:270-293)
* ``WalSyncer`` — handle for lock-free fsync from a separate thread (wal.rs:199-208)
* ``MAX_ENTRY_SIZE`` bound                                       (wal.rs:107)

Design notes (new implementation, not a port): the reference manages 16 MiB
map-aligned windows and pads entries so they never straddle a window
(wal.rs:96-104).  Here the reader maps the whole file and remaps lazily as it
grows, which gives the same zero-copy property without padding logic; the writer
issues unbuffered ``os.write`` so entries become visible to the reader (via page
cache) immediately, and ``sync`` / ``WalSyncer.sync`` force durability.  A torn
tail entry (crash mid-write) fails its crc and cleanly terminates replay.
"""
from __future__ import annotations

import mmap
import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

from .native import native as _native


def _close_quietly(m: mmap.mmap) -> None:
    """Close a mapping, tolerating a transient buffer export (the native
    wal_scan holds the buffer only for the duration of the call); the mapping
    is then released when the last reference drops instead."""
    try:
        m.close()
    except BufferError:
        pass

Tag = int
WalPosition = int

_HEADER = struct.Struct("<IIII")  # magic, crc32(payload), payload len, tag
HEADER_SIZE = _HEADER.size
WAL_MAGIC = 0x314C4157  # b"WAL1" little-endian
POSITION_MAX: WalPosition = (1 << 64) - 1
MAX_ENTRY_SIZE = 64 * 1024 * 1024  # bound on a single entry payload


class WalError(IOError):
    """Corrupt or inconsistent WAL content."""


def walf(path: str) -> Tuple["WalWriter", "WalReader"]:
    """Open (creating if needed) the log at ``path`` (wal.rs:38-50)."""
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    size = os.fstat(fd).st_size
    writer = WalWriter(fd, size, path)
    reader = WalReader(path)
    return writer, reader


class WalWriter:
    """Single-owner appender.  Not thread-safe by design: all writes come from the
    consensus owner task (the reference's single core thread, core_thread/spawned.rs)."""

    __slots__ = ("_fd", "_pos", "_path", "_closed")

    def __init__(self, fd: int, pos: int, path: str) -> None:
        self._fd = fd
        self._pos = pos
        self._path = path
        self._closed = False
        os.lseek(fd, 0, os.SEEK_END)  # append after any recovered content

    def write(self, tag: Tag, payload: bytes) -> WalPosition:
        return self.writev(tag, (payload,))

    def writev(self, tag: Tag, parts: Sequence[bytes]) -> WalPosition:
        """Append one entry assembled from ``parts`` (scatter write, wal.rs:150-198)."""
        assert not self._closed
        length = sum(len(p) for p in parts)
        if length > MAX_ENTRY_SIZE:
            raise WalError(f"entry of {length} bytes exceeds MAX_ENTRY_SIZE")
        if _native is not None:
            # Single-pass native framing (header + parts + crc in one buffer).
            frame_parts: Sequence[bytes] = (_native.frame_entry(tag, list(parts)),)
        else:
            crc = 0
            for p in parts:
                crc = zlib.crc32(p, crc)
            header = _HEADER.pack(WAL_MAGIC, crc, length, tag)
            frame_parts = (header, *parts)
        position = self._pos
        total = HEADER_SIZE + length
        # A short write (ENOSPC, signal) would desynchronize every WAL
        # position recorded downstream — write until complete or fail loudly
        # (the reference asserts written == expected, wal.rs:185).
        written = os.writev(self._fd, list(frame_parts))
        if written != total:
            buf = memoryview(b"".join(frame_parts))
            while written < total:
                n = os.write(self._fd, buf[written:])
                if n <= 0:
                    raise WalError(
                        f"short WAL write: {written}/{total} bytes at {position}"
                    )
                written += n
        self._pos = position + total
        return position

    def position(self) -> WalPosition:
        return self._pos

    def sync(self) -> None:
        os.fsync(self._fd)

    def syncer(self) -> "WalSyncer":
        """An independently-owned fsync handle usable from another thread (wal.rs:199-208)."""
        return WalSyncer(self._path)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            os.close(self._fd)


class WalSyncer:
    """Fsync handle decoupled from the writer: owns its own descriptor so a
    dedicated flusher thread never contends with the appender (wal.rs:199-208,
    used by net_sync.rs:496-560's AsyncWalSyncer)."""

    __slots__ = ("_fd",)

    def __init__(self, path: str) -> None:
        self._fd = os.open(path, os.O_RDWR)

    def sync(self) -> None:
        os.fsync(self._fd)

    def close(self) -> None:
        os.close(self._fd)


class WalReader:
    """Random-access reader over the log; thread-safe.

    Reads go through a whole-file mmap that is lazily re-created when the file has
    grown past the mapped size (the reference's analogue: 16 MiB windows mapped on
    demand, wal.rs:96-104,226-259).  ``cleanup`` drops the mapping so the OS can
    reclaim page cache (wal.rs:302-311 equivalent).
    """

    __slots__ = ("_fd", "_map", "_map_size", "_lock", "_path")

    def __init__(self, path: str) -> None:
        self._fd = os.open(path, os.O_RDONLY)
        self._path = path
        self._map: Optional[mmap.mmap] = None
        self._map_size = 0
        self._lock = threading.Lock()

    # -- mapping management --

    def _ensure_mapped(self, end: int) -> Optional[mmap.mmap]:
        """Map at least [0, end); returns None if the file is still shorter than end."""
        with self._lock:
            if self._map is not None and end <= self._map_size:
                return self._map
            size = os.fstat(self._fd).st_size
            if end > size:
                return None
            if self._map is not None:
                _close_quietly(self._map)
            self._map = mmap.mmap(self._fd, size, prot=mmap.PROT_READ)
            self._map_size = size
            return self._map

    def cleanup(self) -> int:
        """Drop the current mapping; returns number of retained maps (0/1)."""
        with self._lock:
            if self._map is not None:
                _close_quietly(self._map)
                self._map = None
                self._map_size = 0
        return 0

    # -- reads --

    def _read_header(self, position: WalPosition) -> Optional[Tuple[int, int, Tag]]:
        m = self._ensure_mapped(position + HEADER_SIZE)
        if m is None:
            return None
        magic, crc, length, tag = _HEADER.unpack_from(m, position)
        if magic != WAL_MAGIC:
            return None
        return crc, length, tag

    def read(self, position: WalPosition) -> Tuple[Tag, bytes]:
        """Read the entry at ``position``; raises WalError on corruption (wal.rs:226-259)."""
        header = self._read_header(position)
        if header is None:
            raise WalError(f"no valid wal entry at position {position}")
        crc, length, tag = header
        m = self._ensure_mapped(position + HEADER_SIZE + length)
        if m is None:
            raise WalError(f"truncated wal entry at position {position}")
        payload = bytes(
            memoryview(m)[position + HEADER_SIZE : position + HEADER_SIZE + length]
        )
        if zlib.crc32(payload) != crc:
            raise WalError(f"crc mismatch at position {position}")
        return tag, payload

    def iter_until(
        self, end: Optional[WalPosition] = None
    ) -> Iterator[Tuple[WalPosition, Tag, bytes]]:
        """Replay all entries from the start up to ``end`` (or the current file end).

        A torn/corrupt tail entry terminates iteration silently — that is the
        crash-recovery contract (wal.rs:270-293): everything before the tear was
        durable, the tear itself was never acknowledged.
        """
        pos: WalPosition = 0
        if end is None:
            end = os.fstat(self._fd).st_size
        if _native is not None and end > 0:
            m = self._ensure_mapped(end)
            if m is None:
                return
            # Collect the offsets first, then slice the mmap directly
            # (mmap slicing copies): no exported buffer lives across a yield,
            # so concurrent remap/cleanup in other threads stays legal.  A
            # cleanup() landing between yields closes the map under us — the
            # slice then raises ValueError and we re-resolve the mapping.
            entries = _native.wal_scan(m, end)
            for pos, tag, off, length in entries:
                try:
                    payload = m[off : off + length]
                except ValueError:
                    m = self._ensure_mapped(end)
                    if m is None:
                        return
                    payload = m[off : off + length]
                yield pos, tag, payload
            return
        while pos + HEADER_SIZE <= end:
            header = self._read_header(pos)
            if header is None:
                return
            crc, length, tag = header
            if pos + HEADER_SIZE + length > end:
                return
            try:
                tag2, payload = self.read(pos)
            except WalError:
                return
            yield pos, tag2, payload
            pos += HEADER_SIZE + length

    def close(self) -> None:
        self.cleanup()
        os.close(self._fd)
