"""Write-ahead log: append-only tagged entries with crc32 framing and mmap reads.

Capability parity with ``mysticeti-core/src/wal.rs``:

* ``walf(path) -> (WalWriter, WalReader)``                      (wal.rs:38-41)
* 16-byte entry header (magic, crc32, len, tag)                  (wal.rs:110-112,211-223)
* positional addressing: a ``WalPosition`` is the byte offset of the entry header,
  ``POSITION_MAX`` is the reserved "none" position                (wal.rs:31-36)
* reads return memory-mapped views                               (wal.rs:226-259)
* ``iter_until`` replay iterator used for crash recovery         (wal.rs:270-293)
* ``WalSyncer`` — handle for lock-free fsync from a separate thread (wal.rs:199-208)
* ``MAX_ENTRY_SIZE`` bound                                       (wal.rs:107)

Design notes (new implementation, not a port): the reference manages 16 MiB
map-aligned windows and pads entries so they never straddle a window
(wal.rs:96-104).  Here the reader maps the whole file and remaps lazily as it
grows, which gives the same zero-copy property without padding logic; the writer
issues unbuffered ``os.write`` so entries become visible to the reader (via page
cache) immediately, and ``sync`` / ``WalSyncer.sync`` force durability.  A torn
tail entry (crash mid-write) fails its crc and cleanly terminates replay.
"""
from __future__ import annotations

import mmap
import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

from .native import native as _native


def _close_quietly(m: mmap.mmap) -> None:
    """Close a mapping, tolerating a transient buffer export (the native
    wal_scan holds the buffer only for the duration of the call); the mapping
    is then released when the last reference drops instead."""
    try:
        m.close()
    except BufferError:
        pass

Tag = int
WalPosition = int

_HEADER = struct.Struct("<IIII")  # magic, crc32(payload), payload len, tag
HEADER_SIZE = _HEADER.size
WAL_MAGIC = 0x314C4157  # b"WAL1" little-endian
POSITION_MAX: WalPosition = (1 << 64) - 1
MAX_ENTRY_SIZE = 64 * 1024 * 1024  # bound on a single entry payload


class WalError(IOError):
    """Corrupt or inconsistent WAL content."""


def walf(
    path: str, async_writes: Optional[bool] = None
) -> Tuple["WalWriter", "WalReader"]:
    """Open (creating if needed) the log at ``path`` (wal.rs:38-50).

    ``async_writes=False`` forces synchronous appends (no drain thread) —
    the deterministic simulators need it because a real thread's progress
    is wall-clock state, and anything observing it (``pending()`` feeds
    the ingress admission controller's ``wal_backlog`` signal) would leak
    nondeterminism into a seeded virtual-time run."""
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    size = os.fstat(fd).st_size
    writer = WalWriter(fd, size, path, async_writes=async_writes)
    reader = WalReader(path)
    reader._inflight = writer.inflight_get
    reader._writer_flush = writer.flush
    return writer, reader


class WalWriter:
    """Single-owner appender.  Not thread-safe by design: all appends come
    from the consensus owner task (the reference's single core thread,
    core_thread/spawned.rs).

    Appends are ASYNCHRONOUS by default: ``writev`` frames the entry,
    assigns its position, parks the framed bytes in an in-flight map, and
    hands the actual ``pwrite`` to a dedicated writer thread — a ~5 MB
    block entry costs the event loop microseconds instead of a ~37 ms
    blocking write (measured 15% of wall time at saturated load).  Readers
    see in-flight entries through :meth:`inflight_get` (``walf`` wires the
    paired :class:`WalReader` to it), so read-after-write holds even before
    the bytes reach the page cache.

    Durability: WEAKER than synchronous appends for queued entries — until
    the drain thread's pwrite lands, an acknowledged entry lives only in
    process memory, so a plain process crash (OOM/SIGKILL) can lose it; the
    reference's synchronous writev put entries in the page cache, where only
    OS/power failure could.  Callers whose entries become EXTERNALLY VISIBLE
    (an own proposal handed to dissemination) must ``flush()`` first —
    ``Core.try_new_block`` does — restoring the page-cache floor exactly
    where equivocation is at stake.  ``sync`` drains the queue then fsyncs,
    the 1 s syncer thread bounds the fsync loss window, and a crash
    truncates to a torn tail exactly as before (the queue preserves append
    order; the drain thread writes sequentially).
    ``MYSTICETI_SYNC_WAL_WRITES=1`` restores fully synchronous appends.
    A/B at 24k offered tx/s on a single-core host: identical throughput,
    27% lower average commit latency with the writer thread (221 ms vs
    304 ms) — write stalls leave the consensus critical path even when the
    core itself stays busy.
    """

    __slots__ = ("_fd", "_pos", "_path", "_closed", "_async", "_queue",
                 "_inflight", "_inflight_lock", "_thread", "_error")

    def __init__(self, fd: int, pos: int, path: str,
                 async_writes: Optional[bool] = None) -> None:
        self._fd = fd
        self._pos = pos
        self._path = path
        self._closed = False
        os.lseek(fd, 0, os.SEEK_END)  # append after any recovered content
        if async_writes is None:
            async_writes = os.environ.get("MYSTICETI_SYNC_WAL_WRITES") != "1"
        self._async = async_writes
        self._error: Optional[BaseException] = None
        if async_writes:
            import queue as _queue

            self._queue: "_queue.SimpleQueue" = _queue.SimpleQueue()
            self._inflight: dict = {}
            self._inflight_lock = threading.Lock()
            self._thread = threading.Thread(
                target=self._drain, name="wal-writer", daemon=True
            )
            self._thread.start()
        else:
            self._queue = None
            self._inflight = {}
            self._inflight_lock = threading.Lock()
            self._thread = None

    def write(self, tag: Tag, payload: bytes) -> WalPosition:
        return self.writev(tag, (payload,))

    def _frame(self, tag: Tag, parts: Sequence[bytes]) -> Tuple[bytes, int]:
        length = sum(len(p) for p in parts)
        if length > MAX_ENTRY_SIZE:
            raise WalError(f"entry of {length} bytes exceeds MAX_ENTRY_SIZE")
        if _native is not None:
            # Single-pass native framing (header + parts + crc in one buffer).
            frame = _native.frame_entry(tag, list(parts))
        else:
            crc = 0
            for p in parts:
                crc = zlib.crc32(p, crc)
            frame = _HEADER.pack(WAL_MAGIC, crc, length, tag) + b"".join(parts)
        return frame, HEADER_SIZE + length

    def writev(self, tag: Tag, parts: Sequence[bytes]) -> WalPosition:
        """Append one entry assembled from ``parts`` (scatter write, wal.rs:150-198)."""
        assert not self._closed
        if self._error is not None:
            # The drain thread failed (ENOSPC, bad fd): positions already
            # handed out may never land — fail stop, loudly.
            raise self._error
        frame, total = self._frame(tag, parts)
        position = self._pos
        if self._async:
            with self._inflight_lock:
                self._inflight[position] = frame
            self._queue.put(position)
            self._pos = position + total
            return position
        self._pwrite_all(frame, position, total)
        self._pos = position + total
        return position

    def _pwrite_all(self, frame: bytes, position: int, total: int) -> None:
        # A short write (ENOSPC, signal) would desynchronize every WAL
        # position recorded downstream — write until complete or fail loudly
        # (the reference asserts written == expected, wal.rs:185).
        buf = memoryview(frame)
        written = 0
        while written < total:
            n = os.pwrite(self._fd, buf[written:], position + written)
            if n <= 0:
                raise WalError(
                    f"short WAL write: {written}/{total} bytes at {position}"
                )
            written += n

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            if isinstance(item, threading.Event):
                item.set()  # flush marker: everything before it has landed
                continue
            with self._inflight_lock:
                frame = self._inflight.get(item)
            if frame is None:
                continue
            try:
                self._pwrite_all(frame, item, len(frame))
            except BaseException as exc:  # noqa: BLE001 - recorded, re-raised
                self._error = exc
                return
            with self._inflight_lock:
                self._inflight.pop(item, None)

    def inflight_get(self, position: WalPosition) -> Optional[bytes]:
        """Framed bytes of a queued-but-unwritten entry (reader seam).

        Once the drain thread has failed, parked entries will NEVER reach
        disk — serving them as successful reads would hand out data that
        does not exist durably.  Fail-stop propagates to readers too."""
        if self._error is not None:
            raise self._error
        with self._inflight_lock:
            return self._inflight.get(position)

    def pending(self) -> bool:
        """True while acknowledged appends are still queued in process
        memory (cheap gate: callers skip the flush marker round-trip when
        the drain thread is already caught up — the common case)."""
        if not self._async:
            return False
        with self._inflight_lock:
            return bool(self._inflight)

    def flush(self) -> None:
        """Block until every queued append has reached the file."""
        # Drain-thread liveness is real-mode-only state: sim WALs are
        # synchronous (walf() forces async_writes=False), so ``_thread`` is
        # None and these probes are constant in virtual time.
        if not self._async or self._thread is None or not self._thread.is_alive():  # lint: ignore[sim-taint]
            if self._error is not None:
                raise self._error
            return
        marker = threading.Event()
        self._queue.put(marker)
        while not marker.wait(timeout=1.0):
            if self._error is not None:
                raise self._error
            if not self._thread.is_alive():  # lint: ignore[sim-taint] (same: real drain thread only)
                break
        if self._error is not None:
            raise self._error

    def truncate_to(self, position: WalPosition) -> None:
        """Discard a torn tail discovered during recovery.

        Replay stops at the first corrupt entry; everything past it was never
        acknowledged.  Appends must resume AT the tear, not after it: a new
        entry written past the torn bytes would be unreachable on the next
        replay (iteration stops at the tear forever), silently losing every
        subsequent acknowledged write.  Recovery calls this before the first
        post-restart append (block_store.py:open)."""
        assert not self._closed
        assert position <= self._pos
        self.flush()  # nothing should be queued at recovery time; be safe
        os.ftruncate(self._fd, position)
        os.lseek(self._fd, 0, os.SEEK_END)
        self._pos = position

    def position(self) -> WalPosition:
        return self._pos

    def size_bytes(self) -> int:
        """Live log bytes.  For the single-file log this IS the append
        position; the segmented WAL (storage.py) overrides it to sum the
        surviving segments so the ``wal_size_bytes`` gauge reflects disk
        actually held, not lifetime bytes written."""
        return self._pos

    def segment_count(self) -> int:
        return 1

    def note_round(self, round_: int, position: Optional[WalPosition] = None) -> None:
        """Lifecycle hook: the segmented writer (storage.py) tracks the max
        block round per segment as its GC predicate; the single-file log has
        no segments to retire, so this is a no-op."""

    def sync(self) -> None:
        self.flush()
        os.fsync(self._fd)

    def syncer(self) -> "WalSyncer":
        """An independently-owned fsync handle usable from another thread
        (wal.rs:199-208).  Carries a flush hook into this writer: with async
        appends, an fsync that does not drain the queue first would not
        cover acknowledged entries and the 1 s loss-window bound would be a
        lie."""
        return WalSyncer(self._path, flush=self.flush)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self.flush()
            finally:
                if self._thread is not None and self._thread.is_alive():  # lint: ignore[sim-taint] (same: real drain thread only)
                    self._queue.put(None)
                    self._thread.join(timeout=5.0)
                os.close(self._fd)


class WalSyncer:
    """Fsync handle decoupled from the writer: owns its own descriptor so a
    dedicated flusher thread never contends with the appender (wal.rs:199-208,
    used by net_sync.rs:496-560's AsyncWalSyncer)."""

    __slots__ = ("_fd", "_flush")

    def __init__(self, path: str, flush=None) -> None:
        self._fd = os.open(path, os.O_RDWR)
        self._flush = flush

    def sync(self) -> None:
        if self._flush is not None:
            try:
                self._flush()
            except (WalError, OSError):
                # The writer already records and re-raises its own failure
                # on the append path; the fsync of what DID land still runs.
                pass
        os.fsync(self._fd)

    def close(self) -> None:
        os.close(self._fd)


class WalReader:
    """Random-access reader over the log; thread-safe.

    Reads go through a whole-file mmap that is lazily re-created when the file has
    grown past the mapped size (the reference's analogue: 16 MiB windows mapped on
    demand, wal.rs:96-104,226-259).  ``cleanup`` drops the mapping so the OS can
    reclaim page cache (wal.rs:302-311 equivalent).
    """

    __slots__ = ("_fd", "_map", "_map_size", "_lock", "_path", "_inflight",
                 "_writer_flush")

    def __init__(self, path: str) -> None:
        self._fd = os.open(path, os.O_RDONLY)
        self._path = path
        self._map: Optional[mmap.mmap] = None
        self._map_size = 0
        self._lock = threading.Lock()
        # Read-through for the paired writer's queued-but-unwritten entries
        # (async appends): set by walf().  None for standalone readers.
        self._inflight = None
        self._writer_flush = None

    # -- mapping management --

    def _ensure_mapped(self, end: int) -> Optional[mmap.mmap]:
        """Map at least [0, end); returns None if the file is still shorter than end."""
        with self._lock:
            if self._map is not None and end <= self._map_size:
                return self._map
            size = os.fstat(self._fd).st_size
            if end > size:
                return None
            if self._map is not None:
                _close_quietly(self._map)
            self._map = mmap.mmap(self._fd, size, prot=mmap.PROT_READ)
            self._map_size = size
            return self._map

    def cleanup(self) -> int:
        """Drop the current mapping; returns number of retained maps (0/1)."""
        with self._lock:
            if self._map is not None:
                _close_quietly(self._map)
                self._map = None
                self._map_size = 0
        return 0

    # -- reads --

    def _read_header(self, position: WalPosition) -> Optional[Tuple[int, int, Tag]]:
        m = self._ensure_mapped(position + HEADER_SIZE)
        if m is None:
            return None
        magic, crc, length, tag = _HEADER.unpack_from(m, position)
        if magic != WAL_MAGIC:
            return None
        return crc, length, tag

    def read(self, position: WalPosition) -> Tuple[Tag, bytes]:
        """Read the entry at ``position``; raises WalError on corruption (wal.rs:226-259)."""
        if self._inflight is not None:
            # Entry may still be queued in the writer thread: serve it from
            # the in-flight frame so read-after-write never races the disk.
            frame = self._inflight(position)
            if frame is not None:
                _, _, length, tag = _HEADER.unpack_from(frame, 0)
                return tag, frame[HEADER_SIZE:HEADER_SIZE + length]
        header = self._read_header(position)
        if header is None:
            raise WalError(f"no valid wal entry at position {position}")
        crc, length, tag = header
        m = self._ensure_mapped(position + HEADER_SIZE + length)
        if m is None:
            raise WalError(f"truncated wal entry at position {position}")
        payload = bytes(
            memoryview(m)[position + HEADER_SIZE : position + HEADER_SIZE + length]
        )
        if zlib.crc32(payload) != crc:
            raise WalError(f"crc mismatch at position {position}")
        return tag, payload

    def iter_until(
        self, end: Optional[WalPosition] = None
    ) -> Iterator[Tuple[WalPosition, Tag, bytes]]:
        """Replay all entries from the start up to ``end`` (or the current file end).

        A torn/corrupt tail entry terminates iteration silently — that is the
        crash-recovery contract (wal.rs:270-293): everything before the tear was
        durable, the tear itself was never acknowledged.
        """
        pos: WalPosition = 0
        if self._writer_flush is not None:
            # Replay must see every acknowledged append: drain the paired
            # writer's queue before snapshotting the file end.
            self._writer_flush()
        if end is None:
            end = os.fstat(self._fd).st_size
        if _native is not None and end > 0:
            m = self._ensure_mapped(end)
            if m is None:
                return
            # Collect the offsets first, then slice the mmap directly
            # (mmap slicing copies): no exported buffer lives across a yield,
            # so concurrent remap/cleanup in other threads stays legal.  A
            # cleanup() landing between yields closes the map under us — the
            # slice then raises ValueError and we re-resolve the mapping.
            entries = _native.wal_scan(m, end)
            for pos, tag, off, length in entries:
                try:
                    payload = m[off : off + length]
                except ValueError:
                    m = self._ensure_mapped(end)
                    if m is None:
                        return
                    payload = m[off : off + length]
                yield pos, tag, payload
            return
        while pos + HEADER_SIZE <= end:
            header = self._read_header(pos)
            if header is None:
                return
            crc, length, tag = header
            if pos + HEADER_SIZE + length > end:
                return
            try:
                tag2, payload = self.read(pos)
            except WalError:
                return
            yield pos, tag2, payload
            pos += HEADER_SIZE + length

    def iter_from(
        self, start: WalPosition, end: Optional[WalPosition] = None
    ) -> Iterator[Tuple[WalPosition, Tag, bytes]]:
        """Replay entries from ``start`` (an entry boundary) up to ``end``.

        Checkpoint recovery (storage.py) resumes replay at the position the
        checkpoint recorded instead of byte zero.  Same torn-tail contract as
        :meth:`iter_until`; a ``start`` that is not a valid entry boundary
        yields nothing (the caller's replayed-end accounting then treats
        everything past it as torn).
        """
        if start == 0:
            yield from self.iter_until(end)
            return
        if self._writer_flush is not None:
            self._writer_flush()
        if end is None:
            end = os.fstat(self._fd).st_size
        pos: WalPosition = start
        while pos + HEADER_SIZE <= end:
            header = self._read_header(pos)
            if header is None:
                return
            _crc, length, tag = header
            if pos + HEADER_SIZE + length > end:
                return
            try:
                tag2, payload = self.read(pos)
            except WalError:
                return
            yield pos, tag2, payload
            pos += HEADER_SIZE + length

    def close(self) -> None:
        self.cleanup()
        os.close(self._fd)
