"""Deterministic execution plane: account/transfer state machine on commits.

The committed leader sequence is a total order every honest node derives
identically (the same property :mod:`.reconfig` anchors epoch changes on),
which makes it a replicated-state-machine log for free.  This module is the
CONSUMER half of ROADMAP item 3: a deterministic account/transfer runtime
folded over the linearized commits, whose per-commit **state root** becomes
a cross-node safety invariant and the object clients actually wait for
(execution-backed finality, the ACE-runtime shape from PAPERS.md).

* ``ExecTx`` — a typed CREATE/MINT/TRANSFER transaction that rides the
  committed sequence as an ordinary ``Share`` payload prefixed with
  ``EXEC_MAGIC``.  Non-magic payloads (benchmark counters, stamped random
  bytes, reconfig changes) are opaque no-ops — the runtime coexists with
  every existing workload.
* ``ExecutionState`` — the per-node state machine owned by the consensus
  core: folds each committed sub-dag (linearized order, one commit at a
  time, the ``ReconfigState.observe_commit`` pattern) and emits a chained
  per-commit state root.
* **State root** — BLAKE2b-256 over ``prev_root ‖ height ‖ sorted account
  deltas`` (canonical serde encoding, accounts sorted by key).  Every
  commit advances the chain — a commit with no execution transactions
  still produces a new root — so two honest nodes can be compared at
  *every* shared height, and a fork anywhere poisons every later root.

Determinism rules (docs/execution.md):

* Inputs are exactly (previous state, commit height, Share payloads in
  sub-dag linearized order).  No clocks, no RNG, no per-node identity.
* Invalid transactions (bad nonce, overdraft, duplicate create, unknown
  account) are deterministic typed no-ops — every node rejects them with
  the same verdict, so duplicates and garbage cannot fork the chain.
* A payload carrying ``EXEC_MAGIC`` that fails to decode is an opaque
  no-op, exactly like :func:`.reconfig.parse_reconfig_tx` — a garbled
  transaction must not fork honest nodes on whether to error.

Concurrency: mutation is single-owner (the consensus core task calls
:meth:`ExecutionState.observe_commit`), but the ingress plane *probes*
account state from submission threads for pre-consensus admission
(bad-nonce / insufficient-balance shed before consensus pays for the tx),
so the account table is guarded by ``_exec_lock`` (lint GUARDED_FIELDS).
"""
from __future__ import annotations

import hashlib
import threading
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from .serde import Reader, SerdeError, Writer
from .types import Share, StatementBlock

# Share-payload prefix marking an execution transaction.  Same shape as
# RECONFIG_MAGIC: 8 bytes, first byte 0xFF — unreachable for the 8-byte
# little-endian benchmark counters below 2**63.
EXEC_MAGIC = b"\xffEXECTX\x01"

OP_CREATE = 0  # create account with an initial (faucet) balance; nonce must be 0
OP_MINT = 1  # balance += amount on an existing account (nonce-gated)
OP_TRANSFER = 2  # move amount to dest (auto-created at 0); nonce-gated

_OP_NAMES = {OP_CREATE: "create", OP_MINT: "mint", OP_TRANSFER: "transfer"}

# Typed apply verdicts.  The *names* are the metrics label set
# (mysticeti_execution_txs_total{result}) and the ingress shed vocabulary —
# keep them stable.
APPLIED = "applied"
REJECT_EXISTS = "account_exists"
REJECT_UNKNOWN = "unknown_account"
REJECT_BAD_NONCE = "bad_nonce"
REJECT_OVERDRAFT = "insufficient_balance"

MAX_ACCOUNT_KEY_LEN = 64

# Recent (height, root) pairs retained for the /debug document, the gateway
# resume reply, and the chaos state-root audit.  Bounded: old roots are
# recomputable from the WAL and irrelevant to live agreement checks.
ROOT_WINDOW = 1024

GENESIS_ROOT = b"\x00" * 32


@dataclass(frozen=True)
class ExecTx:
    """One typed execution transaction riding the committed sequence."""

    op: int
    account: bytes
    nonce: int = 0
    amount: int = 0
    dest: bytes = b""

    def __post_init__(self) -> None:
        if self.op not in _OP_NAMES:
            raise ValueError(f"unknown execution op {self.op}")
        if not self.account or len(self.account) > MAX_ACCOUNT_KEY_LEN:
            raise ValueError(
                f"account key must be 1..{MAX_ACCOUNT_KEY_LEN} bytes"
            )
        if self.op == OP_TRANSFER:
            if not self.dest or len(self.dest) > MAX_ACCOUNT_KEY_LEN:
                raise ValueError(
                    f"transfer dest must be 1..{MAX_ACCOUNT_KEY_LEN} bytes"
                )
        elif self.dest:
            raise ValueError(f"{_OP_NAMES[self.op]} takes no dest")
        if self.nonce < 0 or self.amount < 0:
            raise ValueError("nonce/amount must be non-negative")

    def to_bytes(self) -> bytes:
        w = Writer()
        w.fixed(EXEC_MAGIC)
        w.u8(self.op)
        w.bytes(self.account)
        w.u64(self.nonce)
        w.u64(self.amount)
        w.bytes(self.dest)
        return w.finish()

    @staticmethod
    def from_bytes(data: bytes) -> "ExecTx":
        r = Reader(data)
        magic = r.fixed(len(EXEC_MAGIC))
        if magic != EXEC_MAGIC:
            raise SerdeError("not an execution transaction")
        op = r.u8()
        account = bytes(r.bytes())
        nonce = r.u64()
        amount = r.u64()
        dest = bytes(r.bytes())
        r.expect_done()
        return ExecTx(op, account, nonce, amount, dest)

    def describe(self) -> str:
        extra = f", dest={self.dest.hex()}" if self.dest else ""
        return (
            f"{_OP_NAMES[self.op]}(account={self.account.hex()}, "
            f"nonce={self.nonce}, amount={self.amount}{extra})"
        )


def parse_exec_tx(payload: bytes) -> Optional[ExecTx]:
    """Decode a Share payload into an :class:`ExecTx`, or None for ordinary
    transactions.  A payload carrying the magic but failing to decode is
    treated as ordinary data (a garbled transaction must not fork honest
    nodes on whether to error — ignoring it is the deterministic choice)."""
    if not payload.startswith(EXEC_MAGIC):
        return None
    try:
        return ExecTx.from_bytes(payload)
    except (SerdeError, ValueError):
        return None


@dataclass(frozen=True)
class ExecutionResult:
    """Outcome of folding one committed sub-dag."""

    height: int
    root: bytes
    applied: int
    rejected: int
    # typed verdict name -> count for this commit (APPLIED included)
    verdicts: Tuple[Tuple[str, int], ...] = ()


class ExecutionState:
    """Deterministic account/transfer state machine on the committed sequence.

    Single-owner mutation (the consensus core task calls
    :meth:`observe_commit` / :meth:`adopt` / :meth:`recover`); concurrent
    *probes* from ingress submission threads go through :meth:`probe` under
    the same lock.
    """

    def __init__(self, metrics=None) -> None:
        self._exec_lock = threading.Lock()
        # account key -> (balance, nonce).  Guarded by _exec_lock (lint
        # GUARDED_FIELDS): the core task folds commits while ingress
        # submission threads probe balances for pre-consensus admission.
        self._exec_accounts: Dict[bytes, Tuple[int, int]] = {}
        self.last_height = 0
        self.root = GENESIS_ROOT
        self.recent_roots: Deque[Tuple[int, bytes]] = deque(maxlen=ROOT_WINDOW)
        self.applied_total = 0
        self.rejected_total = 0
        self.metrics = metrics

    # -- queries ---------------------------------------------------------

    def probe(self, account: bytes) -> Optional[Tuple[int, int]]:
        """(balance, nonce) snapshot, or None for an unknown account.
        Advisory by design: in-flight committed transactions may move the
        account before a submission folded against this snapshot lands."""
        with self._exec_lock:
            return self._exec_accounts.get(account)

    def account_count(self) -> int:
        with self._exec_lock:
            return len(self._exec_accounts)

    def root_at(self, height: int) -> Optional[bytes]:
        """The chained root at ``height`` if still in the recent window."""
        for h, root in reversed(self.recent_roots):
            if h == height:
                return root
            if h < height:
                break
        return None

    def admission_verdict(self, tx: ExecTx) -> Optional[str]:
        """Pre-consensus admission check for the ingress plane: a typed
        reject for transactions that are *already* doomed against current
        state, None for plausibly-valid ones.

        Deliberately weaker than :meth:`_apply`: a nonce *ahead* of the
        account (earlier transactions in flight) and a CREATE for a not-yet
        -existing account are admitted — only verdicts that cannot be cured
        by in-flight traffic (stale nonce, overdraft beyond current funds
        plus any pending mint is still a heuristic — we only shed what is
        wrong *now*) are shed before consensus pays for the transaction."""
        snapshot = self.probe(tx.account)
        if tx.op == OP_CREATE:
            return REJECT_EXISTS if snapshot is not None else None
        if snapshot is None:
            return REJECT_UNKNOWN
        balance, nonce = snapshot
        if tx.nonce < nonce:
            return REJECT_BAD_NONCE
        if tx.op == OP_TRANSFER and tx.nonce == nonce and tx.amount > balance:
            return REJECT_OVERDRAFT
        return None

    # -- the fold --------------------------------------------------------

    def _apply(self, tx: ExecTx, deltas: Dict[bytes, Tuple[int, int]]) -> str:
        """Apply one transaction against the account table (lock held by
        the caller), recording touched accounts into ``deltas``."""
        accounts = self._exec_accounts
        if tx.op == OP_CREATE:
            if tx.account in accounts:
                return REJECT_EXISTS
            if tx.nonce != 0:
                return REJECT_BAD_NONCE
            accounts[tx.account] = (tx.amount, 1)
            deltas[tx.account] = accounts[tx.account]
            return APPLIED
        entry = accounts.get(tx.account)
        if entry is None:
            return REJECT_UNKNOWN
        balance, nonce = entry
        if tx.nonce != nonce:
            return REJECT_BAD_NONCE
        if tx.op == OP_MINT:
            accounts[tx.account] = (balance + tx.amount, nonce + 1)
            deltas[tx.account] = accounts[tx.account]
            return APPLIED
        # OP_TRANSFER
        if tx.amount > balance:
            return REJECT_OVERDRAFT
        dest_balance, dest_nonce = accounts.get(tx.dest, (0, 0))
        if tx.dest == tx.account:
            # Self-transfer: balance unchanged, nonce still consumed.
            accounts[tx.account] = (balance, nonce + 1)
            deltas[tx.account] = accounts[tx.account]
            return APPLIED
        accounts[tx.account] = (balance - tx.amount, nonce + 1)
        accounts[tx.dest] = (dest_balance + tx.amount, dest_nonce)
        deltas[tx.account] = accounts[tx.account]
        deltas[tx.dest] = accounts[tx.dest]
        return APPLIED

    def observe_commit(
        self, height: int, blocks: List[StatementBlock]
    ) -> Optional[ExecutionResult]:
        """Fold one committed sub-dag (linearized block order) into the
        state and advance the root chain.  Returns None when the commit was
        already folded (crash replay re-delivers committed heights —
        exactly the ``ReconfigState.observe_commit`` skip)."""
        if height <= self.last_height:
            return None
        verdicts: Dict[str, int] = {}
        deltas: Dict[bytes, Tuple[int, int]] = {}
        with self._exec_lock:
            for block in blocks:
                for st in block.statements:
                    if not isinstance(st, Share):
                        continue
                    tx = parse_exec_tx(bytes(st.transaction))
                    if tx is None:
                        continue
                    verdict = self._apply(tx, deltas)
                    verdicts[verdict] = verdicts.get(verdict, 0) + 1
        # Chained root: prev ‖ height ‖ sorted account deltas.  The digest
        # input is canonical serde bytes, so it is identical wherever the
        # same commit folds over the same predecessor state.
        h = hashlib.blake2b(digest_size=32)
        h.update(self.root)
        w = Writer()
        w.u64(height)
        w.u32(len(deltas))
        for key in sorted(deltas):
            balance, nonce = deltas[key]
            w.bytes(key)
            w.u64(balance)
            w.u64(nonce)
        h.update(w.finish())
        self.root = h.digest()
        self.last_height = height
        self.recent_roots.append((height, self.root))
        applied = verdicts.get(APPLIED, 0)
        rejected = sum(v for k, v in verdicts.items() if k != APPLIED)
        self.applied_total += applied
        self.rejected_total += rejected
        if self.metrics is not None:
            for verdict, count in verdicts.items():
                self.metrics.mysticeti_execution_txs_total.labels(
                    verdict
                ).inc(count)
            self.metrics.mysticeti_execution_height.set(height)
            self.metrics.mysticeti_execution_accounts.set(
                len(self._exec_accounts)
            )
        return ExecutionResult(
            height,
            self.root,
            applied,
            rejected,
            tuple(sorted(verdicts.items())),
        )

    # -- durability ------------------------------------------------------

    def to_bytes(self) -> bytes:
        """Canonical full-state encoding (checkpoints / snapshot manifests).
        Accounts are sorted by key, so two nodes on the same root encode
        byte-identically."""
        w = Writer()
        w.u64(self.last_height)
        w.fixed(self.root)
        with self._exec_lock:
            items = sorted(self._exec_accounts.items())
        w.u32(len(items))
        for key, (balance, nonce) in items:
            w.bytes(key)
            w.u64(balance)
            w.u64(nonce)
        w.u64(self.applied_total)
        w.u64(self.rejected_total)
        return w.finish()

    def recover(self, data: bytes) -> None:
        """Adopt a persisted state wholesale (checkpoint recovery)."""
        if not data:
            return
        r = Reader(data)
        last_height = r.u64()
        root = r.fixed(32)
        accounts: Dict[bytes, Tuple[int, int]] = {}
        for _ in range(r.u32()):
            key = bytes(r.bytes())
            accounts[key] = (r.u64(), r.u64())
        applied_total = r.u64()
        rejected_total = r.u64()
        r.expect_done()
        with self._exec_lock:
            self._exec_accounts = accounts
        self.last_height = last_height
        self.root = root
        self.applied_total = applied_total
        self.rejected_total = rejected_total
        self.recent_roots.clear()
        if last_height:
            self.recent_roots.append((last_height, root))

    def adopt(self, data: bytes) -> bool:
        """Snapshot catch-up: adopt a remote execution state iff it is
        AHEAD of ours (the :meth:`.reconfig.ReconfigState.adopt_chain`
        shape — a remote at or behind our height carries nothing we need
        and is ignored).  Trust model: the manifest rode the same
        quorum-anchored snapshot the commit baseline did; the adopted root
        is cross-checked against the fleet by the chaos state-root audit
        and re-verified implicitly by every later locally-folded commit."""
        if not data:
            return False
        r = Reader(data)
        remote_height = r.u64()
        if remote_height <= self.last_height:
            return False
        self.recover(data)
        return True

    def state(self) -> dict:
        """Live introspection document (/debug/consensus)."""
        return {
            "height": self.last_height,
            "root": self.root.hex(),
            "accounts": self.account_count(),
            "applied_total": self.applied_total,
            "rejected_total": self.rejected_total,
            "recent_roots": [
                {"height": h, "root": root.hex()}
                for h, root in list(self.recent_roots)[-16:]
            ],
        }
