"""Observability: prometheus series, exact-percentile histograms, busy timers.

Capability parity with ``mysticeti-core/src/metrics.rs`` + ``stat.rs`` +
``prometheus.rs``:

* the full metric inventory (metrics.rs:36-87), including the benchmark-defining
  series ``benchmark_duration`` / ``latency_s`` / ``latency_squared_s``
  (metrics.rs:31-33) that the orchestrator's measurement scraper consumes;
* ``PreciseHistogram`` — exact p50/90/99 percentiles over a bounded sample
  buffer, surfaced as gauges by a periodic ``MetricReporter`` task
  (stat.rs:8-100, metrics.rs:534-601);
* utilization timers — context managers accumulating busy-microseconds per
  labeled code section, the reference's poor-man's profiler (metrics.rs:615-666);
* an HTTP ``/metrics`` endpoint (prometheus.rs:31-49) served by asyncio.
"""
from __future__ import annotations

import asyncio
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)

LATENCY_SEC_BUCKETS = [
    0.1, 0.25, 0.5, 0.75, 1.0, 1.25, 1.5, 1.75, 2.0, 2.5, 5.0, 10.0, 20.0,
    30.0, 60.0, 90.0,
]

BENCHMARK_DURATION = "benchmark_duration"
LATENCY_S = "latency_s"
LATENCY_SQUARED_S = "latency_squared_s"


class PreciseHistogram:
    """Exact-percentile histogram over a reporting window (stat.rs:8-100).

    The reference's reporter DRAINS the channel each sweep
    (metrics.rs:534-601): published percentiles describe the last window,
    not the whole run.  Same semantics here — ``report_precise`` clears the
    buffer after publishing.  Within a window the buffer is a uniform
    reservoir sample (Algorithm R) of every observation, so a window busier
    than ``max_samples`` still yields representative percentiles instead of
    freezing on its first ``max_samples`` arrivals (the warmup seconds, the
    worst possible sample).  ``count``/``sum`` stay cumulative for ``avg``.
    """

    __slots__ = ("samples", "count", "sum", "max_samples", "_window_count",
                 "_rng", "_np_rng")

    def __init__(self, max_samples: int = 100_000) -> None:
        import random

        self.samples: List[float] = []
        self.count = 0
        self.sum = 0.0
        self.max_samples = max_samples
        self._window_count = 0
        self._rng = random.Random(0xC0FFEE)
        self._np_rng = None  # built lazily on the first batched observe

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        self._window_count += 1
        if len(self.samples) < self.max_samples:
            self.samples.append(value)
        else:
            # Reservoir (Algorithm R): keep each of the window's n
            # observations with probability max_samples/n.
            j = self._rng.randrange(self._window_count)
            if j < self.max_samples:
                self.samples[j] = value

    def observe_many(self, values) -> None:
        """Vectorized observe over a numpy array (the commit path hands us
        thousands of samples per batch at load): one sum + one batched
        reservoir step instead of n Python calls."""
        n = len(values)
        if n == 0:
            return
        self.count += n
        self.sum += float(values.sum())
        cap = self.max_samples
        fill = min(cap - len(self.samples), n)
        if fill > 0:
            self.samples.extend(float(v) for v in values[:fill])
            self._window_count += fill
            values = values[fill:]
            n -= fill
        if n <= 0:
            return
        # Algorithm R, batched: the k-th remaining value is the
        # (window_count + k)-th of the window; it replaces a random slot
        # with probability cap / (window_count + k).  Slot draws are one
        # vectorized uniform per batch — a Python randrange per sample
        # measured 7% of a saturated node's core (round-5 profile).
        import numpy as np

        if self._np_rng is None:
            self._np_rng = np.random.default_rng(0xC0FFEE)
        idx = np.arange(self._window_count + 1, self._window_count + n + 1)
        self._window_count += n
        slots = (self._np_rng.random(n) * idx).astype(np.int64)
        hit = slots < cap
        for slot, value in zip(slots[hit], np.asarray(values)[hit]):
            self.samples[slot] = float(value)

    def pcts(self, pcts: Sequence[int]) -> Optional[Dict[int, float]]:
        if not self.samples:
            return None
        ordered = sorted(self.samples)
        out = {}
        for pct in pcts:
            idx = min(len(ordered) - 1, int(len(ordered) * pct / 100))
            out[pct] = ordered[idx]
        return out

    def avg(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def clear(self) -> None:
        self.samples.clear()
        self._window_count = 0


class Metrics:
    """Registers every series on a fresh registry (metrics.rs:121-424)."""

    def __init__(self, registry: Optional[CollectorRegistry] = None) -> None:
        self.registry = registry or CollectorRegistry()
        r = self.registry

        def counter(name, doc, labels=()):
            return Counter(name, doc, labelnames=labels, registry=r)

        def gauge(name, doc, labels=()):
            return Gauge(name, doc, labelnames=labels, registry=r)

        def histogram(name, doc, labels=(), buckets=LATENCY_SEC_BUCKETS):
            return Histogram(name, doc, labelnames=labels, buckets=buckets, registry=r)

        # Benchmark-defining series (metrics.rs:31-33).
        self.benchmark_duration = counter(BENCHMARK_DURATION, "benchmark duration, s")
        self.latency_s = histogram(
            LATENCY_S, "end-to-end tx latency", labels=("workload",)
        )
        self.latency_squared_s = counter(
            LATENCY_SQUARED_S, "sum of squared latencies", labels=("workload",)
        )

        # Consensus progress.
        self.committed_leaders_total = counter(
            "committed_leaders_total", "decided leaders", labels=("authority", "status")
        )
        self.leader_timeout_total = counter("leader_timeout_total", "leader timeouts")
        self.inter_block_latency_s = histogram(
            "inter_block_latency_s", "inter-block latency", labels=("workload",)
        )
        self.threshold_clock_round = gauge("threshold_clock_round", "current round")
        self.commit_round = gauge("commit_round", "last committed round")
        self.ready_new_block = counter(
            "ready_new_block", "proposal readiness reasons", labels=("reason",)
        )

        # Block store.
        self.block_store_unloaded_blocks = counter(
            "block_store_unloaded_blocks", "cache evictions"
        )
        self.block_store_loaded_blocks = counter(
            "block_store_loaded_blocks", "wal reloads"
        )
        self.block_store_entries = counter("block_store_entries", "stored blocks")
        self.wal_mappings = gauge("wal_mappings", "live mmap windows")
        self.wal_size_bytes = gauge(
            "wal_size_bytes",
            "live write-ahead log bytes across all surviving segments "
            "(storage lifecycle: bounded by GC, not lifetime bytes written)",
        )
        # Storage lifecycle plane (storage.py).
        self.wal_segments = gauge(
            "wal_segments", "live WAL segment files (1 = single-file log)"
        )
        self.wal_reclaimed_bytes_total = counter(
            "wal_reclaimed_bytes_total",
            "WAL bytes deleted by segment garbage collection below the "
            "retired round floor",
        )
        self.checkpoint_last_commit_index = gauge(
            "checkpoint_last_commit_index",
            "commit height anchoring the newest durable checkpoint "
            "(recovery replays only WAL entries after it)",
        )

        # Epoch reconfiguration (reconfig.py).
        self.mysticeti_epoch = gauge(
            "mysticeti_epoch",
            "current consensus epoch (advances when a committed "
            "committee-change transaction derives a new committee)",
        )
        self.mysticeti_epoch_transitions_total = counter(
            "mysticeti_epoch_transitions_total",
            "epoch boundaries crossed since boot (commit-anchored committee "
            "switches, including those re-derived on recovery)",
        )
        self.mysticeti_committee_digest_info = gauge(
            "mysticeti_committee_digest_info",
            "info gauge naming the active committee: value is the epoch, "
            "label carries the committee digest prefix",
            labels=("digest",),
        )

        # Core owner queue (core_lock_* in metrics.rs:51-53; the dispatcher
        # queue is this framework's core lock).
        self.core_lock_enqueued = counter(
            "core_lock_enqueued", "commands submitted to the core owner"
        )
        self.core_lock_dequeued = counter(
            "core_lock_dequeued", "commands executed by the core owner"
        )

        # Handlers.
        self.block_handler_pending_certificates = gauge(
            "block_handler_pending_certificates", "pending fast-path certs"
        )
        self.commit_handler_pending_certificates = gauge(
            "commit_handler_pending_certificates", "pending commit certs"
        )

        # Sync.
        self.missing_blocks_total = counter("missing_blocks_total", "missing refs seen")
        self.blocks_suspended = counter("blocks_suspended", "parked blocks")
        self.block_sync_requests_sent = counter(
            "block_sync_requests_sent", "sync requests", labels=("peer",)
        )
        self.block_sync_requests_failed = counter(
            "block_sync_requests_failed", "refs peers did not have"
        )
        self.block_sync_requests_received = counter(
            "block_sync_requests_received", "sync requests served",
            labels=("peer",),
        )
        self.block_receive_latency = histogram(
            "block_receive_latency",
            "proposal-to-receipt latency of peer blocks",
            labels=("authority",),
        )
        self.add_block_latency = histogram(
            "add_block_latency",
            "proposal-to-acceptance latency of peer blocks",
            labels=("authority",),
        )
        self.connected_nodes = gauge("connected_nodes", "live peer connections")
        self.connection_latency = histogram(
            "connection_latency", "peer rtt", labels=("peer",),
            buckets=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0],
        )
        # Fleet causal trace plane (spans.py + tools/fleet_trace.py).
        self.dissemination_transit_seconds = histogram(
            "dissemination_transit_seconds",
            "one-way wire transit of block push frames from each peer, "
            "measured from the tag-12 sender timestamp (clamped at zero; "
            "the raw signed value rides in the trace for skew estimation)",
            labels=("peer",),
            buckets=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                     5.0],
        )
        self.flight_recorder_dumps_total = counter(
            "flight_recorder_dumps_total",
            "flight-recorder ring dumps written, by trigger (shutdown, "
            "slo-alert, safety-failure)",
            labels=("trigger",),
        )
        # Broadcast-once mesh data plane (synchronizer.FrameCache +
        # network write coalescing): what the encode-once fan-out saved,
        # what the sockets actually carried, and which sends backpressure
        # silently discarded.
        self.dissemination_encode_reuse_total = counter(
            "dissemination_encode_reuse_total",
            "dissemination frames served from the shared frame cache "
            "instead of being rebuilt per subscriber (N subscribers at one "
            "cursor = 1 build + N-1 reuses)",
        )
        self.mesh_frames_coalesced_total = counter(
            "mesh_frames_coalesced_total",
            "mesh frames that shipped in the same scatter-gather "
            "writelines batch as an earlier frame (one syscall + one "
            "drain for the whole batch)",
        )
        self.mesh_wire_bytes_total = counter(
            "mesh_wire_bytes_total",
            "bytes moved over validator mesh sockets (headers + payloads)",
            labels=("direction",),
        )
        self.connection_send_drops_total = counter(
            "connection_send_drops_total",
            "non-blocking mesh sends discarded because the peer's bounded "
            "send queue was full (backpressure; previously silent)",
            labels=("peer",),
        )

        # TPU verifier.
        self.verified_signatures_total = counter(
            "verified_signatures_total", "batched signature verifications",
            labels=("backend", "outcome"),
        )
        self.verify_batch_size = histogram(
            "verify_batch_size", "signature batch sizes",
            buckets=[1, 8, 32, 64, 128, 256, 512, 1024, 4096],
        )
        # Verifier hot-path telemetry (the ROADMAP's north-star seam).
        self.verify_dispatch_batch_size = histogram(
            "verify_dispatch_batch_size",
            "signatures per ACTUAL backend dispatch (after aggregation "
            "skips; verify_batch_size is the collector flush size)",
            buckets=[1, 8, 32, 64, 128, 256, 512, 1024, 4096],
        )
        self.verify_padding_wasted_total = counter(
            "verify_padding_wasted_total",
            "padding lanes dispatched (padded bucket size minus actual "
            "signatures)", labels=("backend",),
        )
        self.verify_route_total = counter(
            "verify_route_total", "hybrid router decisions", labels=("route",)
        )
        self.verify_route_estimate_error_s = histogram(
            "verify_route_estimate_error_s",
            "|estimated - actual| dispatch time of routed batches",
            buckets=[0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                     1.0, 5.0],
        )
        self.verifier_service_queue_depth = gauge(
            "verifier_service_queue_depth",
            "verify requests queued or dispatching in the verifier service",
        )
        self.verifier_service_inflight = gauge(
            "verifier_service_inflight",
            "in-flight verify requests per service client connection",
            labels=("connection",),
        )
        # Staged dispatch pipeline (verify_pipeline.py): the collector may
        # hold several dispatches in flight; these series say how full the
        # window runs and where each dispatch's time goes.
        self.verify_pipeline_inflight = gauge(
            "verify_pipeline_inflight",
            "signature dispatches currently in flight through the staged "
            "verify pipeline (bounded by verify_pipeline_depth)",
        )
        self.verify_pipeline_depth = gauge(
            "verify_pipeline_depth",
            "current bounded in-flight window of the verify pipeline "
            "(occupancy = verify_pipeline_inflight / verify_pipeline_depth)",
        )
        self.verify_pipeline_stage_seconds = histogram(
            "verify_pipeline_stage_seconds",
            "per-dispatch time in each verify pipeline stage",
            labels=("stage",),
            buckets=[0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25,
                     0.5, 1.0, 5.0],
        )
        # Native data plane (native/mysticeti_native.cpp): which native
        # functions resolved in THIS process — an info series (value
        # constant 1) so A/B artifacts and fleetmon can tell which path a
        # run actually measured.  The "any" row is always present: 1 with
        # the extension, 0 on the pure-Python fallback (no toolchain,
        # build failure, MYSTICETI_NO_NATIVE=1).
        self.mysticeti_native_active = gauge(
            "mysticeti_native_active",
            "info series: native data-plane functions resolved (fn=any "
            "summarizes extension presence)",
            labels=("fn",),
        )
        from .native import active_functions as _native_active_functions

        _active_fns = _native_active_functions()
        for _fn in _active_fns:
            self.mysticeti_native_active.labels(_fn).set(1)
        self.mysticeti_native_active.labels("any").set(1 if _active_fns else 0)
        # Batched decode+digest batches routed off the event loop
        # (core_task.DataPlaneOffload) — stage wall time measured IN the
        # offload worker, the verify_pipeline_stage_seconds sibling for the
        # host data plane.
        self.dataplane_offload_seconds = histogram(
            "dataplane_offload_seconds",
            "per-batch time in each data-plane offload stage, measured in "
            "the offload worker thread (queue wait excluded)",
            labels=("stage",),
            buckets=[0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25,
                     0.5, 1.0, 5.0],
        )
        # Zero-tax data plane (the no-chip flavor parity work): which
        # batches never touched the socket, what the wire actually carried,
        # and the window the adaptive collector chose.
        self.verify_shortcircuit_total = counter(
            "verify_shortcircuit_total",
            "signature batches completed without touching the verifier "
            "service socket (reason: backend-cpu = service advertised a "
            "CPU-only backend, router = cost model chose the in-process "
            "oracle, breaker = circuit open)",
            labels=("reason",),
        )
        self.verify_wire_bytes_total = counter(
            "verify_wire_bytes_total",
            "bytes moved over the verifier-service socket by this client",
            labels=("direction",),
        )
        self.verify_collector_window_seconds = gauge(
            "verify_collector_window_seconds",
            "collection window the batching collector last armed "
            "(arrival-rate-adaptive, ceilinged by the dispatch-cost window)",
        )
        self.verifier_fallback_total = counter(
            "verifier_fallback_total",
            "signature batches degraded to the CPU oracle because the "
            "accelerator path was unavailable (circuit breaker open or "
            "dispatch failed)",
        )
        self.verifier_reconnect_total = counter(
            "verifier_reconnect_total",
            "verifier-service client connections torn down and retried",
        )

        # Fleet health plane (health.py): consensus-level health signals
        # derived from state the node already has, refreshed by the
        # HealthProbe sampler; the same probe serves the /health diagnosis
        # document next to /healthz.
        self.mysticeti_health_round_advance_rate = gauge(
            "mysticeti_health_round_advance_rate",
            "threshold-clock rounds advanced per second (EMA over probe "
            "samples)",
        )
        self.mysticeti_health_commit_rate = gauge(
            "mysticeti_health_commit_rate",
            "committed sub-dags per second (EMA over probe samples)",
        )
        self.mysticeti_health_frontier_skew_rounds = gauge(
            "mysticeti_health_frontier_skew_rounds",
            "DAG frontier skew: max peer round seen minus own round "
            "(positive = this node is behind the fleet)",
        )
        self.mysticeti_health_authority_lag_rounds = gauge(
            "mysticeti_health_authority_lag_rounds",
            "per-authority frontier lag: own round minus the authority's "
            "last block round seen here (a growing lag names the straggler)",
            labels=("authority",),
        )
        self.mysticeti_health_leader_timeout_total = counter(
            "mysticeti_health_leader_timeout_total",
            "leader timeouts attributed to the authority whose leader slot "
            "stalled the round",
            labels=("authority",),
        )
        self.mysticeti_health_verifier_breaker_open = gauge(
            "mysticeti_health_verifier_breaker_open",
            "1 while the hybrid verifier circuit breaker is open (degraded "
            "to the CPU oracle)",
        )
        self.mysticeti_health_verifier_pinned = gauge(
            "mysticeti_health_verifier_pinned",
            "1 while short-circuit routing is pinned to the in-process "
            "oracle (service advertised a CPU-only backend)",
        )
        self.mysticeti_health_wal_backlog = gauge(
            "mysticeti_health_wal_backlog",
            "1 while acknowledged WAL appends are still queued in process "
            "memory (the async drain is behind)",
        )
        self.mysticeti_health_status = gauge(
            "mysticeti_health_status",
            "1 when no SLO alert is firing, 0 while degraded (the /health "
            "readiness verdict)",
        )
        self.mysticeti_health_slo_alerts_total = counter(
            "mysticeti_health_slo_alerts_total",
            "SLO watchdog alerts raised, named by kind, the indicted "
            "authority (empty = whole node), and the pipeline stage",
            labels=("kind", "authority", "stage"),
        )
        self.commit_critical_path_seconds = histogram(
            "commit_critical_path_seconds",
            "per committed leader: time each pipeline stage spent on the "
            "receive->verify->dag_add->proposal_wait->commit->finalize "
            "critical path (requires span tracing; see health.py)",
            labels=("stage",),
            buckets=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                     5.0, 10.0, 30.0],
        )

        # Host attribution plane (profiling.py accountant + hostattr.py):
        # where host time goes, per subsystem, and what the event loop pays
        # for it.  The cpu-seconds counter is fed by the sampling profiler's
        # census (active when MYSTICETI_PROFILE is set); the loop-lag /
        # blocking-call / convoy series are always on.
        self.mysticeti_cpu_seconds_total = counter(
            "mysticeti_cpu_seconds_total",
            "sampled CPU seconds attributed to each subsystem of the "
            "declarative registry (profiling.SUBSYSTEMS), split by thread "
            "class (loop / verifier / wal / aux)",
            labels=("subsystem", "thread_class"),
        )
        self.mysticeti_cpu_us_per_leader = gauge(
            "mysticeti_cpu_us_per_leader",
            "per-committed-leader normalized subsystem cost: sampled CPU "
            "microseconds per committed leader (the PERF_ATTR budget rows)",
            labels=("subsystem",),
        )
        self.mysticeti_loop_lag_seconds = histogram(
            "mysticeti_loop_lag_seconds",
            "asyncio loop scheduling lag: scheduled-vs-actual callback "
            "delta of the loop-lag probe (hostattr.LoopLagProbe)",
            buckets=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5],
        )
        self.mysticeti_loop_lag_p99_seconds = gauge(
            "mysticeti_loop_lag_p99_seconds",
            "p99 loop scheduling lag over the probe's bounded window (the "
            "loop-lag SLO watchdog input; fleetmon dashboard column)",
        )
        self.mysticeti_gil_convoy_ratio = gauge(
            "mysticeti_gil_convoy_ratio",
            "fraction of census ticks where >=2 threads were runnable at "
            "once — with one interpreter lock, a proxy for GIL convoying",
        )
        self.mysticeti_blocking_calls_total = counter(
            "mysticeti_blocking_calls_total",
            "synchronous core-owner commands that held the event loop past "
            "MYSTICETI_BLOCKING_CALL_MS (the dynamic twin of the "
            "async-blocking lint rule), by command site",
            labels=("site",),
        )
        self.mysticeti_blocking_call_last_ms = gauge(
            "mysticeti_blocking_call_last_ms",
            "duration of the most recent detected blocking call, ms",
        )
        self.mysticeti_jax_compiles_total = counter(
            "mysticeti_jax_compiles_total",
            "JAX backend compile events observed in this process "
            "(jax.monitoring; a climbing counter mid-run means a shape "
            "escaped the fixed dispatch buckets)",
        )
        self.mysticeti_jax_compile_seconds_total = counter(
            "mysticeti_jax_compile_seconds_total",
            "cumulative seconds spent in JAX backend compilation",
        )
        self.mysticeti_jax_cache_hits_total = counter(
            "mysticeti_jax_cache_hits_total",
            "persistent compile-cache hits (kernels loaded instead of "
            "recompiled)",
        )
        self.mysticeti_jax_cache_misses_total = counter(
            "mysticeti_jax_cache_misses_total",
            "persistent compile-cache misses (full compile paid)",
        )
        self.mysticeti_device_transfer_bytes_total = counter(
            "mysticeti_device_transfer_bytes_total",
            "bytes moved between host and device on the verifier hot path "
            "(to_device = packed signature blobs, from_device = verdict "
            "fetches)",
            labels=("direction",),
        )
        self.mysticeti_verify_occupancy_fraction = gauge(
            "mysticeti_verify_occupancy_fraction",
            "fraction of cumulative verify-dispatch time in each phase "
            "(device = device-busy, pack = host packing, fetch = "
            "result-wait), from the verify_pipeline stage timers",
            labels=("phase",),
        )

        # Overload-resilient ingress plane (ingress.py): the admission-
        # controlled mempool's accounting.  Every transaction a node refuses
        # is on mysticeti_ingress_shed_total — silent drops were the PR 10
        # connection_send_drops_total lesson.
        self.mysticeti_ingress_shed_total = counter(
            "mysticeti_ingress_shed_total",
            "transactions refused (or deferred) by the ingress plane, by "
            "reason: admission (AIMD rate), mempool_transactions / "
            "mempool_bytes (pool caps), lane_cap (per-client fairness "
            "lane), duplicate (dedup window), notify_backpressure (commit "
            "notifications a slow gateway client lost), soft_cap_deferred "
            "(re-queued for the NEXT proposal — deferred, not lost)",
            labels=("reason",),
        )
        self.mysticeti_ingress_admitted_total = counter(
            "mysticeti_ingress_admitted_total",
            "transactions admitted into the mempool (offered = admitted + "
            "shed, per the typed SubmitResult contract)",
        )
        self.mysticeti_ingress_admitted_rate = gauge(
            "mysticeti_ingress_admitted_rate",
            "current AIMD-admitted transaction rate ceiling (tx/s) — cut "
            "multiplicatively on core congestion, raised additively while "
            "healthy",
        )
        self.mysticeti_ingress_mempool_transactions = gauge(
            "mysticeti_ingress_mempool_transactions",
            "transactions pending in the bounded ingress mempool",
        )
        self.mysticeti_ingress_mempool_bytes = gauge(
            "mysticeti_ingress_mempool_bytes",
            "bytes pending in the bounded ingress mempool",
        )
        self.mysticeti_ingress_shed_mode = gauge(
            "mysticeti_ingress_shed_mode",
            "1 while the admission controller is in shed mode (congestion "
            "detected; transitions land in the flight recorder)",
        )
        self.mysticeti_ingress_gateway_clients = gauge(
            "mysticeti_ingress_gateway_clients",
            "live client connections on the ingress gateway listener",
        )
        self.mysticeti_transaction_dedup_total = counter(
            "mysticeti_transaction_dedup_total",
            "duplicate/unknown transaction observations in the fast-path "
            "vote aggregator (previously log lines only)",
            labels=("kind",),
        )

        # Consensus decision ledger (decisions.py): why each leader slot
        # decided — the structured replacement for the old per-authority
        # direct-commit/indirect-skip committed_leaders_total labels.
        self.mysticeti_commit_decision_total = counter(
            "mysticeti_commit_decision_total",
            "leader-slot decisions recorded by the decision ledger, by the "
            "rule that decided (direct = blames/certificates in the slot's "
            "own wave, indirect = a committed anchor one wave ahead) and "
            "outcome (commit | skip); each decided slot counts exactly once",
            labels=("rule", "outcome"),
        )
        self.mysticeti_decision_rounds_behind = histogram(
            "mysticeti_decision_rounds_behind",
            "how many rounds behind the DAG frontier a leader slot was when "
            "it decided (direct decisions sit near wave_length - 1; large "
            "values mean slots lingered undecided and resolved indirectly)",
            buckets=[2.0, 3.0, 4.0, 6.0, 9.0, 15.0, 30.0, 60.0, 120.0],
        )

        # Client-perceived finality SLI plane (finality.py): the gateway's
        # 16-byte ingress keys joined across the transaction lifecycle.
        self.mysticeti_e2e_finality_seconds = histogram(
            "mysticeti_e2e_finality_seconds",
            "phase-split end-to-end finality latency for count-sampled "
            "ingress keys: admission (submit -> mempool accept), proposal "
            "(accept -> drained into a block proposal), commit (proposal -> "
            "leader sequence commit), finalize (commit -> observer "
            "finalized), execute (finalized -> execution state machine "
            "folded the commit), notify (finalized/executed -> gateway "
            "notification queued), total (submit -> finalized, or submit -> "
            "EXECUTED when the execution plane is on)",
            labels=("phase",),
            buckets=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                     5.0, 10.0, 30.0],
        )
        self.mysticeti_e2e_finality_p50_seconds = gauge(
            "mysticeti_e2e_finality_p50_seconds",
            "rolling p50 of sampled submit -> finalized latency (exact over "
            "the finality tracker's recent-sample window; feeds fleetmon)",
        )
        self.mysticeti_e2e_finality_p99_seconds = gauge(
            "mysticeti_e2e_finality_p99_seconds",
            "rolling p99 of sampled submit -> finalized latency — the "
            "finality-p99 SLO watchdog input and the fleetmon readiness "
            "gate column",
        )
        self.mysticeti_client_finality_p50_seconds = gauge(
            "mysticeti_client_finality_p50_seconds",
            "rolling p50 of CLIENT-observed submit -> commit-notification "
            "latency from closed-loop generators (cross-checks the "
            "server-side series in one artifact)",
        )
        self.mysticeti_client_finality_p99_seconds = gauge(
            "mysticeti_client_finality_p99_seconds",
            "rolling p99 of CLIENT-observed submit -> commit-notification "
            "latency from closed-loop generators",
        )

        # Deterministic execution plane (execution.py): the account/transfer
        # state machine folded over the committed sequence.
        self.mysticeti_execution_txs_total = counter(
            "mysticeti_execution_txs_total",
            "execution transactions folded through the state machine by "
            "verdict: applied, or a typed deterministic reject "
            "(bad_nonce, insufficient_balance, unknown_account, "
            "account_exists) — rejects consume the commit slot but not "
            "account state",
            labels=("result",),
        )
        self.mysticeti_execution_height = gauge(
            "mysticeti_execution_height",
            "highest commit height folded through the execution state "
            "machine (trails the committed sequence by at most the "
            "in-flight syncer pass; a growing gap means the fold stalled)",
        )
        self.mysticeti_execution_accounts = gauge(
            "mysticeti_execution_accounts",
            "live accounts in the execution state machine's balance table "
            "(checkpoint tail size scales with this)",
        )

        # Robustness / chaos engineering.
        self.crash_recovery_total = counter(
            "crash_recovery_total",
            "node boots that recovered state by replaying a non-empty WAL",
        )
        self.chaos_faults_total = counter(
            "chaos_faults_total",
            "faults injected by the deterministic chaos engine",
            labels=("kind",),
        )
        # Byzantine adversary plane (adversary.py + docs/adversary.md):
        # what the honest path detected and to whom it attributes it.
        self.mysticeti_equivocation_detected_total = counter(
            "mysticeti_equivocation_detected_total",
            "distinct conflicting blocks observed at one (authority, round) "
            "in the DAG index — a double proposal, attributed to the "
            "equivocating authority (includes the benign post-torn-tail "
            "self-equivocation; each extra digest counts once)",
            labels=("authority",),
        )
        self.mysticeti_invalid_blocks_total = counter(
            "mysticeti_invalid_blocks_total",
            "blocks rejected on the receive path, attributed by authority "
            "and reason: signature (verifier rejected the Ed25519 check), "
            "structure (consensus-rule check failed; attributed to the "
            "claimed author), malformed (undecodable block bytes; "
            "attributed to the DELIVERING peer)",
            labels=("authority", "reason"),
        )
        self.mysticeti_malformed_frames_total = counter(
            "mysticeti_malformed_frames_total",
            "malformed mesh frames (garbage length prefix, oversized "
            "frame, undecodable payload) that severed the delivering "
            "connection, by peer",
            labels=("peer",),
        )
        # Determinism sanitizer plane (detsan.py + docs/static-analysis.md):
        # wall-clock reads reaching package code while the deterministic
        # virtual-time loop is running.  MUST stay zero in any healthy sim —
        # a non-zero count is a reproducibility leak the sim-taint lint
        # missed, attributed to the reading call-site (module:line).
        self.mysticeti_detsan_wallclock_reads_total = counter(
            "mysticeti_detsan_wallclock_reads_total",
            "un-gated time.monotonic()/time()/perf_counter() reads from "
            "package code under simulation, caught by the detsan tripwire "
            "(strict mode raises WallClockLeak instead), by call-site",
            labels=("site",),
        )
        self.mysticeti_leader_wait_skipped_total = counter(
            "mysticeti_leader_wait_skipped_total",
            "proposal-gating waits skipped because the round's leader had "
            "not produced a locally-accepted block within the liveness "
            "horizon (crashed, withholding, or signing invalidly), by the "
            "leader waited-for",
            labels=("authority",),
        )

        # Utilization timers (metrics.rs:615-666).
        self.utilization_timer_us = counter(
            "utilization_timer", "busy time per section, us", labels=("proc",)
        )

        # Exact-percentile channels (stat.rs), reported as gauges.
        self._precise: Dict[str, PreciseHistogram] = {}
        self._pct_gauge = gauge(
            "histogram_pct", "exact percentiles", labels=("name", "pct")
        )
        for name in (
            "transaction_certified_latency",
            "certificate_committed_latency",
            "transaction_committed_latency",
            "proposed_block_size_bytes",
            "proposed_block_transaction_count",
            "proposed_block_vote_count",
            "blocks_per_commit_count",
            "sub_dags_per_commit_count",
            "block_commit_latency",
        ):
            self._precise[name] = PreciseHistogram()
            setattr(self, name, self._precise[name])
        self.quorum_receive_latency = PreciseHistogram()
        self._precise["quorum_receive_latency"] = self.quorum_receive_latency

    def observe_latency_batch(self, workload: str, latencies) -> None:
        """Vectorized ``latency_s.observe`` + ``latency_squared_s.inc`` over a
        numpy array of samples — one bucket-count pass instead of a labels()
        lookup and a 16-bucket walk per transaction (the per-tx path dominated
        the commit observer at load).  Falls back to the plain loop if the
        prometheus_client internals ever change shape.
        """
        import numpy as np

        key = ("latency_batch", workload)
        cached = self.__dict__.get(key)
        if cached is None:
            cached = (
                self.latency_s.labels(workload),
                self.latency_squared_s.labels(workload),
            )
            self.__dict__[key] = cached
        hist, squared = cached
        squared.inc(float(np.square(latencies).sum()))
        try:
            ubs = hist._upper_bounds  # finite bounds + +Inf last
            buckets = hist._buckets
            total = hist._sum
        except AttributeError:  # pragma: no cover - client internals moved
            for v in latencies:
                hist.observe(float(v))
            return
        # le-semantics: first upper bound >= sample (side="left" keeps
        # boundary samples in their bucket, matching observe()).
        idx = np.searchsorted(np.asarray(ubs[:-1]), latencies, side="left")
        counts = np.bincount(idx, minlength=len(ubs))
        for i, c in enumerate(counts):
            if c:
                buckets[i].inc(int(c))
        total.inc(float(latencies.sum()))

    @contextmanager
    def utilization_timer(self, proc: str):
        """Drop-guard busy counter (metrics.rs:615-666)."""
        start = time.monotonic()
        try:
            yield
        finally:
            self.utilization_timer_us.labels(proc).inc(
                int((time.monotonic() - start) * 1e6)
            )

    def report_precise(self) -> None:
        """One reporter sweep: publish exact percentiles, then DRAIN
        (metrics.rs:534-601 — the reference's histogram channel empties per
        sweep, so gauges track the last window; a quiet window keeps the
        previous published value)."""
        for name, hist in self._precise.items():
            pcts = hist.pcts((50, 90, 99))
            if pcts is None:
                continue
            for pct, value in pcts.items():
                self._pct_gauge.labels(name, str(pct)).set(value)
            hist.clear()

    def expose(self) -> bytes:
        return generate_latest(self.registry)


class MetricReporter:
    """Periodic exact-percentile publisher (metrics.rs:534-601, 60 s cadence)."""

    def __init__(self, metrics: Metrics, interval_s: float = 60.0) -> None:
        self.metrics = metrics
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None

    def start(self) -> "MetricReporter":
        self._task = spawn_logged(self._run(), log, name="metric-reporter")
        return self

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            self.metrics.report_precise()

    def stop(self, final: bool = False) -> None:
        """Cancel the periodic task; ``final=True`` publishes one last
        percentile sweep so an orderly shutdown never loses the window that
        accumulated since the previous 60 s tick (short runs lose their
        ENTIRE sample set without it)."""
        if self._task is not None:
            self._task.cancel()
        if final:
            self.metrics.report_precise()


async def serve_metrics(metrics: Metrics, host: str, port: int,
                        health_probe=None, flight_recorder=None,
                        consensus_debug=None):
    """Minimal asyncio HTTP endpoint (prometheus.rs:31-49): ``/metrics`` for
    the scraper, ``/healthz`` (200 + uptime) for liveness probes, and — when
    a :class:`~mysticeti_tpu.health.HealthProbe` is wired — ``/health``, the
    readiness/diagnosis JSON document (503 while an SLO alert is firing, so
    the route doubles as a readiness gate).  With a
    :class:`~mysticeti_tpu.flight_recorder.FlightRecorder` wired,
    ``/debug/flight-recorder`` serves the live event-ring dump (the same
    canonical document the SIGTERM/alert dumps write).  ``consensus_debug``
    is a zero-arg callable returning the live consensus-state document (DAG
    frontier, undecided slots, threshold-clock round, last-K decision
    records) served on ``/debug/consensus``."""
    import json as _json

    started = time.monotonic()

    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request = await reader.readline()  # e.g. b"GET /healthz HTTP/1.1"
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.split()
            path = parts[1].decode(errors="replace") if len(parts) > 1 else "/"
            status = b"200 OK"
            if path.split("?", 1)[0] == "/healthz":
                body = (
                    '{"status":"ok","uptime_s":%.3f}\n'
                    % (time.monotonic() - started)
                ).encode()
                content_type = b"application/json"
            elif (
                path.split("?", 1)[0] == "/debug/flight-recorder"
                and flight_recorder is not None
            ):
                body = flight_recorder.snapshot_bytes() + b"\n"
                content_type = b"application/json"
            elif (
                path.split("?", 1)[0] == "/debug/consensus"
                and consensus_debug is not None
            ):
                doc = consensus_debug()
                body = (_json.dumps(doc, sort_keys=True) + "\n").encode()
                content_type = b"application/json"
            elif path.split("?", 1)[0] == "/health" and health_probe is not None:
                doc = health_probe.diagnosis()
                body = (_json.dumps(doc, sort_keys=True) + "\n").encode()
                content_type = b"application/json"
                if doc.get("status") != "ok":
                    status = b"503 Service Unavailable"
            else:
                # Anything else serves the scrape (back-compat: the
                # orchestrator scraper GETs /metrics).
                body = metrics.expose()
                content_type = b"text/plain; version=0.0.4"
            writer.write(
                b"HTTP/1.1 " + status + b"\r\nContent-Type: " + content_type
                + b"\r\n"
                + f"Content-Length: {len(body)}\r\n\r\n".encode()
                + body
            )
            await writer.drain()
        finally:
            writer.close()

    return await asyncio.start_server(handle, host=host, port=port)
