# lint: ignore-module[sim-taint] — real socket plane: the deterministic
# loop's selector refuses socket registration (_NullSelector), so nothing
# in this module can execute inside a seeded sim; simulated_network.py is
# the virtual-time twin.
"""Validator mesh networking: wire protocol, framing, TCP transport, RTT probes.

Capability parity with ``mysticeti-core/src/network.rs``:

* ``NetworkMessage`` taxonomy {SubscribeOwnFrom, Blocks, RequestBlocks,
  RequestBlocksResponse, BlockNotFound} (network.rs:35-46) + embedded
  Ping/Pong RTT probe (network.rs:33,324-406,563-574)
* 4-byte length-prefixed frames, 16 MiB cap (network.rs:216,397-459)
* handshake magic + authority-index exchange (network.rs:214-217,244-292)
* per-peer reconnect-forever workers (network.rs:218-242)
* per-peer RTT estimate feeding the latency-weighted fetcher and the
  max-latency connection breaker (network.rs:378-381)

Transport design difference (documented, not accidental): the reference races
active+passive connections per peer; here the lower authority index dials and
the higher accepts — same full-mesh + reconnect capability with half the
connection-management states.  ``Connection`` is a pair of asyncio queues, so
the simulated network (simulated_network.py) is a drop-in replacement.

Broadcast-once data plane (endpoint-local; on-wire bytes unchanged):

* **encode-once fan-out** — dissemination streams enqueue
  :class:`EncodedFrame` objects from the shared
  :class:`~mysticeti_tpu.synchronizer.FrameCache`, so N-1 subscribers at the
  same cursor ship one serialization instead of re-encoding per peer;
* **scatter-gather write coalescing** — ``write_loop`` drains every queued
  message non-blocking and ships the batch as one
  ``writer.writelines([hdr, payload, ...])`` + a single ``drain()`` (headers
  are fresh immutable objects per write: a 3.12+ transport may hold frame N
  zero-copy in its buffer while we build frame N+1).  Ping/Pong jump the
  batch — RTT probes never queue behind bulk payloads;
* **zero-copy receive** — after the handshake the transport is switched onto
  :class:`_FrameReceiver` (``asyncio.BufferedProtocol``): the event loop
  ``recv_into``s directly into a reusable per-connection assembly buffer,
  frames surface as memoryviews, ``decode_message`` makes block payloads
  sub-views, and ``StatementBlock.from_bytes`` materializes exactly one
  ``bytes`` per block for the canonical cache.

``MYSTICETI_MESH_LEGACY=1`` forces the pre-r10 path (per-peer encode,
per-frame write+drain, StreamReader receive) — the A/B baseline for
``tools/mesh_ab.py`` and a safety valve; both endpoints interoperate either
way because the frames are byte-identical.
"""
from __future__ import annotations

import asyncio
import collections
import dataclasses
import os
import random
import sys
import time
from typing import Dict, List, Optional, Tuple

from .serde import Reader, SerdeError, Writer
from .tracing import logger
from .utils.tasks import spawn_logged
from .types import BlockReference, RoundNumber, StatementBlock

log = logger(__name__)

HANDSHAKE_MAGIC = 0x7C9A_11B7
MAX_FRAME = 16 * 1024 * 1024
PING_INTERVAL_S = 30.0
# Byte cap on one coalesced writelines batch: enough to amortize the
# syscall/drain over many small frames, small enough that a deep queue of
# multi-MB frames still hits transport flow control per batch instead of
# buffering the whole queue.
MAX_COALESCE_BYTES = 1 << 20


def mesh_legacy() -> bool:
    """True when ``MYSTICETI_MESH_LEGACY=1``: run the pre-broadcast-once
    data plane (per-peer encode, per-frame write, stream receive).  Read
    per connection setup, not cached — tests and the A/B harness flip it
    between runs in one process."""
    return os.environ.get("MYSTICETI_MESH_LEGACY", "") == "1"

def jittered_backoff(delay: float, rng: random.Random) -> float:
    """Uniform [0.5, 1.5)x jitter around an exponential-backoff delay.

    A bare doubling schedule synchronizes reconnect storms: every dialer that
    lost the same peer at the same moment retries on the same beat, hammering
    the recovering node in lockstep bursts.  The multiplicative jitter keeps
    the expected delay while decorrelating the fleet; callers pass a SEEDED
    rng so simulated runs stay reproducible.
    """
    return delay * (0.5 + rng.random())


_MSG_SUBSCRIBE = 1
_MSG_BLOCKS = 2
_MSG_REQUEST = 3
_MSG_RESPONSE = 4
_MSG_NOT_FOUND = 5
_MSG_PING = 6
_MSG_PONG = 7
_MSG_SUBSCRIBE_OTHERS = 8
_MSG_REQUEST_SNAPSHOT = 9
_MSG_SNAPSHOT = 10
_MSG_REQUEST_SNAPSHOT_STREAM = 11
_MSG_BLOCKS_TIMESTAMPED = 12
# Client gateway tags (ingress.py).  These ride the same length-prefixed
# framing and codec but flow ONLY on the gateway listener (client <->
# validator), never on the validator mesh — a mesh peer that predates them
# would reset the connection per the §7 soft-extension rule, and none is
# ever emitted there.
_MSG_GATEWAY_SUBMIT = 13
_MSG_GATEWAY_SUBMIT_REPLY = 14
_MSG_GATEWAY_SUBSCRIBE_COMMITS = 15
_MSG_GATEWAY_COMMITS = 16
# Epoch reconfiguration (reconfig.py): the sender's epoch + committee digest,
# exchanged right after the fixed 12-byte hello and re-broadcast on every
# epoch switch.  A soft wire extension per docs/wire-format.md §7 (tag 17):
# only sent when ``Parameters.reconfig`` is on; receivers that predate the
# tag reset the connection.
_MSG_EPOCH_INFO = 17


@dataclasses.dataclass(frozen=True)
class SubscribeOwnFrom:
    round: RoundNumber


@dataclasses.dataclass(frozen=True)
class SubscribeOthersFrom:
    """Helper-stream request (synchronizer.rs:169-205's dormant
    ``disseminate_others_blocks``, made live behind a Parameters knob):
    "relay AUTHORITY's blocks you hold, from this round on" — sent to a
    helper peer when the authority itself is unreachable.  A soft wire
    extension per docs/wire-format.md §7: receivers that predate the tag
    reset the connection, so senders only emit it when the knob is on."""

    authority: int
    round: RoundNumber


@dataclasses.dataclass(frozen=True)
class RequestSnapshot:
    """Snapshot catch-up ask (storage.py): "my committed height is
    ``commit_height``; if I am far behind, send me your commit baseline".
    A soft wire extension per docs/wire-format.md §7 — only sent when
    ``StorageParameters.snapshot_catchup`` is on; receivers that predate
    the tag reset the connection."""

    commit_height: int


@dataclasses.dataclass(frozen=True)
class SnapshotResponse:
    """The serving node's :class:`~mysticeti_tpu.storage.SnapshotManifest`
    (opaque canonical bytes).  The block window itself is only shipped on an
    explicit :class:`RequestSnapshotStream` — every qualifying peer answers
    the ask with a manifest (cheap), but the receiver adopts exactly one and
    pulls the bulk window from that peer alone."""

    manifest: bytes


@dataclasses.dataclass(frozen=True)
class RequestSnapshotStream:
    """Post-adoption bulk ask: "stream me every block you hold from
    ``from_round`` up" — sent to the ONE peer whose manifest was adopted;
    the window arrives as ordinary ``Blocks`` frames, decoded and re-hashed
    by the receiver like any push stream."""

    from_round: int


@dataclasses.dataclass(frozen=True)
class Blocks:
    blocks: Tuple[bytes, ...]  # serialized StatementBlocks (zero re-encode)


@dataclasses.dataclass(frozen=True)
class TimestampedBlocks(Blocks):
    """A ``Blocks`` push frame stamped with the sender's clocks at send time
    (fleet causal tracing, tools/fleet_trace.py): ``sent_monotonic_ns`` is
    the sender's runtime clock (detects wall-clock jumps between frames),
    ``sent_wall_ns`` its wall clock — the receiver's arrival time minus it
    is the RAW per-link transit the skew estimator aligns.  A soft wire
    extension per docs/wire-format.md §7 (tag 12): receivers that predate
    the tag reset the connection, so senders only emit it when
    ``SynchronizerParameters.timestamp_frames`` is on.  Subclasses
    ``Blocks`` so every receive path handles it unchanged."""

    sent_monotonic_ns: int = 0
    sent_wall_ns: int = 0


def wall_jump_us(prev: Tuple[int, int], cur: Tuple[int, int]) -> int:
    """|Δwall − Δmonotonic| between two consecutive sender stamp pairs
    ``(sent_monotonic_ns, sent_wall_ns)``, in microseconds.

    Between frames both sender clocks advance by real elapsed time, so the
    two deltas agree to within slew; a large disagreement means the
    sender's WALL clock stepped (NTP jump) between the frames — the
    receiver must discard that frame's wall-derived transit sample, which
    is the reason the monotonic stamp rides the wire at all."""
    dw = cur[1] - prev[1]
    dm = cur[0] - prev[0]
    return abs(dw - dm) // 1000


@dataclasses.dataclass(frozen=True)
class RequestBlocks:
    references: Tuple[BlockReference, ...]


@dataclasses.dataclass(frozen=True)
class RequestBlocksResponse:
    blocks: Tuple[bytes, ...]


@dataclasses.dataclass(frozen=True)
class BlockNotFound:
    references: Tuple[BlockReference, ...]


@dataclasses.dataclass(frozen=True)
class GatewaySubmit:
    """Client -> gateway: submit transactions to the admission-controlled
    mempool (wire tag 13, docs/wire-format.md §5b).  ``client`` names the
    fairness lane (empty = the connection's own lane); ``priority`` != 0
    asks for the priority drain class (subject to the lane caps — priority
    weights the round-robin, it does not bypass admission)."""

    client: bytes
    priority: int
    transactions: Tuple[bytes, ...]


# GatewaySubmitReply.status values (SUBMIT -> ACK/QUEUED/SHED).
GATEWAY_ACK = 0  # all accepted, mempool shallow
GATEWAY_QUEUED = 1  # all accepted, mempool past the queued watermark: slow down
GATEWAY_SHED = 2  # some/all rejected; retry_after_ms + reason say why/when


@dataclasses.dataclass(frozen=True)
class GatewaySubmitReply:
    """Gateway -> client: the typed submission verdict (wire tag 14).  A
    SHED reply is the explicit-backpressure contract: ``retry_after_ms``
    tells a closed-loop client when the admission controller expects
    capacity, ``reason`` (utf-8) names the first rejection cause."""

    status: int
    accepted: int
    shed: int
    retry_after_ms: int
    reason: bytes


@dataclasses.dataclass(frozen=True)
class GatewaySubscribeCommits:
    """Client -> gateway: stream commit notifications from ``from_height``
    (exclusive) on (wire tag 15).  Notifications carry the 16-byte ingress
    keys of committed transactions, the same keys the mempool dedups on.

    ``want_details`` (soft suffix, wire-format §5b) opts the subscriber in
    to the tag-16 detail suffix (leader round + commit timestamp) — an
    opt-in because a pre-r17 client would reset the connection on the
    longer notification frames (§7).  ``want_executed`` (second-tier soft
    suffix, r20) additionally opts in to the EXECUTED result suffix (the
    state root after the execution plane folded the commit) and, on the
    wire, forces the ``want_details`` byte to be written explicitly —
    suffix tiers are strictly ordered."""

    from_height: int
    want_details: int = 0
    want_executed: int = 0


@dataclasses.dataclass(frozen=True)
class GatewayCommitNotification:
    """Gateway -> client: transactions sequenced by the committed sub-dag at
    ``height`` (wire tag 16), identified by their 16-byte ingress keys.

    ``leader_round`` / ``committed_ts_ns`` form the soft detail suffix
    (wire-format §5b): the sequencing leader's round and the node's
    runtime commit timestamp, so clients compute finality without
    scraping ``/metrics``.  Encoded only when nonzero AND the subscriber
    asked (``want_details``); absent on the wire they decode as 0.

    ``executed_root`` is the second-tier EXECUTED result suffix (r20): the
    execution plane's chained state root after folding this commit —
    non-empty only for ``want_executed`` subscribers on nodes running the
    execution state machine.  Writing it forces the detail pair onto the
    wire (tiers are strictly ordered); absent it decodes as ``b""``.  A
    notification with ``height > 0`` and NO keys is the synthetic resume
    reply: it pins the node's current executed height/root for a
    resuming subscriber."""

    height: int
    keys: Tuple[bytes, ...]
    leader_round: int = 0
    committed_ts_ns: int = 0
    executed_root: bytes = b""


@dataclasses.dataclass(frozen=True)
class EpochInfo:
    """Sender's reconfiguration coordinates (wire tag 17): current epoch and
    the 32-byte committee digest (reconfig.committee_digest).  Advisory —
    a mismatch is logged and counted, never a reason to sever (the peer may
    simply not have processed the boundary commit yet; the committed
    sequence itself converges the fleet)."""

    epoch: int
    digest: bytes


@dataclasses.dataclass(frozen=True)
class Ping:
    nanos: int


@dataclasses.dataclass(frozen=True)
class Pong:
    nanos: int


NetworkMessage = object


def encode_message(msg: NetworkMessage) -> bytes:
    if _native_encode_frame is not None:
        # Native whole-frame serialization for the Blocks-shaped fan-out
        # payloads (tags 2/4/12): one call builds the entire body with the
        # GIL released instead of a per-block Writer append loop.
        # Byte-identical to the Writer path below — pinned by the golden
        # corpus and the data-plane parity suite.  Exact type checks: a
        # TimestampedBlocks IS a Blocks (subclass), so dispatch must not
        # collapse the stamped header.
        t = type(msg)
        if t is Blocks or t is RequestBlocksResponse:
            return _native_encode_frame(
                _MSG_BLOCKS if t is Blocks else _MSG_RESPONSE,
                False, 0, 0, msg.blocks,
            )
        if t is TimestampedBlocks:
            return _native_encode_frame(
                _MSG_BLOCKS_TIMESTAMPED, True,
                msg.sent_monotonic_ns, msg.sent_wall_ns, msg.blocks,
            )
    w = Writer()
    if isinstance(msg, SubscribeOwnFrom):
        w.u8(_MSG_SUBSCRIBE).u64(msg.round)
    elif isinstance(msg, SubscribeOthersFrom):
        w.u8(_MSG_SUBSCRIBE_OTHERS).u64(msg.authority).u64(msg.round)
    elif isinstance(msg, TimestampedBlocks):
        # Before the Blocks branch: a TimestampedBlocks IS a Blocks.
        w.u8(_MSG_BLOCKS_TIMESTAMPED)
        w.u64(msg.sent_monotonic_ns).u64(msg.sent_wall_ns)
        w.u32(len(msg.blocks))
        for b in msg.blocks:
            w.bytes(b)
    elif isinstance(msg, Blocks):
        w.u8(_MSG_BLOCKS).u32(len(msg.blocks))
        for b in msg.blocks:
            w.bytes(b)
    elif isinstance(msg, RequestBlocks):
        w.u8(_MSG_REQUEST).u32(len(msg.references))
        for r in msg.references:
            r.encode(w)
    elif isinstance(msg, RequestBlocksResponse):
        w.u8(_MSG_RESPONSE).u32(len(msg.blocks))
        for b in msg.blocks:
            w.bytes(b)
    elif isinstance(msg, BlockNotFound):
        w.u8(_MSG_NOT_FOUND).u32(len(msg.references))
        for r in msg.references:
            r.encode(w)
    elif isinstance(msg, Ping):
        w.u8(_MSG_PING).u64(msg.nanos)
    elif isinstance(msg, Pong):
        w.u8(_MSG_PONG).u64(msg.nanos)
    elif isinstance(msg, RequestSnapshot):
        w.u8(_MSG_REQUEST_SNAPSHOT).u64(msg.commit_height)
    elif isinstance(msg, SnapshotResponse):
        w.u8(_MSG_SNAPSHOT).bytes(msg.manifest)
    elif isinstance(msg, RequestSnapshotStream):
        w.u8(_MSG_REQUEST_SNAPSHOT_STREAM).u64(msg.from_round)
    elif isinstance(msg, EpochInfo):
        w.u8(_MSG_EPOCH_INFO).u64(msg.epoch).bytes(msg.digest)
    elif isinstance(msg, GatewaySubmit):
        w.u8(_MSG_GATEWAY_SUBMIT).bytes(msg.client).u8(1 if msg.priority else 0)
        w.u32(len(msg.transactions))
        for tx in msg.transactions:
            w.bytes(tx)
    elif isinstance(msg, GatewaySubmitReply):
        w.u8(_MSG_GATEWAY_SUBMIT_REPLY).u8(msg.status)
        w.u32(msg.accepted).u32(msg.shed).u64(msg.retry_after_ms)
        w.bytes(msg.reason)
    elif isinstance(msg, GatewaySubscribeCommits):
        w.u8(_MSG_GATEWAY_SUBSCRIBE_COMMITS).u64(msg.from_height)
        # Soft suffixes (§5b): omitted when default so pre-r17 gateways
        # (and the roundtrip equality tests) see the original short frame.
        # The second tier (want_executed, r20) forces the first byte to be
        # written explicitly — a reader cannot skip a tier.
        if msg.want_executed:
            w.u8(1 if msg.want_details else 0).u8(1)
        elif msg.want_details:
            w.u8(1)
    elif isinstance(msg, GatewayCommitNotification):
        w.u8(_MSG_GATEWAY_COMMITS).u64(msg.height).u32(len(msg.keys))
        for key in msg.keys:
            w.bytes(key)
        # Soft suffixes (§5b): leader round + commit timestamp, emitted only
        # to subscribers that sent want_details (the gateway constructs
        # default-0 notifications for everyone else).  The EXECUTED result
        # suffix (r20) forces the detail pair onto the wire even when zero.
        if msg.executed_root:
            w.u64(msg.leader_round).u64(msg.committed_ts_ns)
            w.bytes(msg.executed_root)
        elif msg.leader_round or msg.committed_ts_ns:
            w.u64(msg.leader_round).u64(msg.committed_ts_ns)
    else:  # pragma: no cover
        raise SerdeError(f"unknown message {type(msg)}")
    return w.finish()


def decode_message(data) -> NetworkMessage:
    """Decode one frame payload (``bytes`` or ``memoryview``).

    With a memoryview input — the zero-copy receive path — the block
    payloads inside ``Blocks``/``RequestBlocksResponse`` come back as
    sub-views over the caller's buffer; ``StatementBlock.from_bytes``
    materializes each exactly once for the canonical cache.  Everything
    else (references, digests, the snapshot manifest) is materialized here.
    """
    if _native_parse_spans is not None and len(data) > 0 \
            and data[0] in _NATIVE_PARSE_TAGS:
        # Native batched parse for the Blocks-shaped payloads: the whole
        # body is validated in C (GIL released for the walk) and only the
        # per-block sub-views are built in Python — the last step that
        # must touch Python objects.  Rejection cases and error messages
        # are byte-identical to the Reader path (parity corpus).
        try:
            tag, mono_ns, wall_ns, spans = _native_parse_spans(data)
        except ValueError as exc:
            raise SerdeError(str(exc)) from None
        blocks = tuple(data[off : off + ln] for off, ln in spans)
        if tag == _MSG_BLOCKS:
            return Blocks(blocks)
        if tag == _MSG_RESPONSE:
            return RequestBlocksResponse(blocks)
        return TimestampedBlocks(
            blocks, sent_monotonic_ns=mono_ns, sent_wall_ns=wall_ns
        )
    r = Reader(data)
    tag = r.u8()
    if tag == _MSG_SUBSCRIBE:
        msg: NetworkMessage = SubscribeOwnFrom(r.u64())
    elif tag == _MSG_SUBSCRIBE_OTHERS:
        msg = SubscribeOthersFrom(r.u64(), r.u64())
    elif tag == _MSG_BLOCKS:
        msg = Blocks(tuple(r.bytes() for _ in range(r.u32())))
    elif tag == _MSG_REQUEST:
        msg = RequestBlocks(tuple(BlockReference.decode(r) for _ in range(r.u32())))
    elif tag == _MSG_RESPONSE:
        msg = RequestBlocksResponse(tuple(r.bytes() for _ in range(r.u32())))
    elif tag == _MSG_NOT_FOUND:
        msg = BlockNotFound(tuple(BlockReference.decode(r) for _ in range(r.u32())))
    elif tag == _MSG_PING:
        msg = Ping(r.u64())
    elif tag == _MSG_PONG:
        msg = Pong(r.u64())
    elif tag == _MSG_REQUEST_SNAPSHOT:
        msg = RequestSnapshot(r.u64())
    elif tag == _MSG_SNAPSHOT:
        # Manifests are materialized at decode (never a view): the adopted
        # one is persisted to the WAL and must outlive the receive buffer.
        msg = SnapshotResponse(bytes(r.bytes()))
    elif tag == _MSG_REQUEST_SNAPSHOT_STREAM:
        msg = RequestSnapshotStream(r.u64())
    elif tag == _MSG_EPOCH_INFO:
        msg = EpochInfo(r.u64(), bytes(r.bytes()))
    elif tag == _MSG_BLOCKS_TIMESTAMPED:
        monotonic_ns, wall_ns = r.u64(), r.u64()
        msg = TimestampedBlocks(
            tuple(r.bytes() for _ in range(r.u32())),
            sent_monotonic_ns=monotonic_ns,
            sent_wall_ns=wall_ns,
        )
    elif tag == _MSG_GATEWAY_SUBMIT:
        # Materialized (never views): submitted transactions outlive the
        # receive buffer — they sit in the mempool until proposed.
        client = bytes(r.bytes())
        priority = r.u8()
        msg = GatewaySubmit(
            client, priority, tuple(bytes(r.bytes()) for _ in range(r.u32()))
        )
    elif tag == _MSG_GATEWAY_SUBMIT_REPLY:
        msg = GatewaySubmitReply(
            r.u8(), r.u32(), r.u32(), r.u64(), bytes(r.bytes())
        )
    elif tag == _MSG_GATEWAY_SUBSCRIBE_COMMITS:
        from_height = r.u64()
        # §5b suffixes, tier by tier: absent on frames from older clients.
        want_details = r.u8() if not r.done() else 0
        want_executed = r.u8() if not r.done() else 0
        msg = GatewaySubscribeCommits(from_height, want_details, want_executed)
    elif tag == _MSG_GATEWAY_COMMITS:
        height = r.u64()
        keys = tuple(bytes(r.bytes()) for _ in range(r.u32()))
        if not r.done():
            # §5b suffixes: leader round + commit timestamp, then the
            # optional EXECUTED result root (r20).
            leader_round, committed_ts_ns = r.u64(), r.u64()
            executed_root = bytes(r.bytes()) if not r.done() else b""
            msg = GatewayCommitNotification(
                height, keys, leader_round, committed_ts_ns, executed_root
            )
        else:
            msg = GatewayCommitNotification(height, keys)
    else:
        raise SerdeError(f"unknown message tag {tag}")
    r.expect_done()
    return msg


class EncodedFrame:
    """A message plus its cached frame payload (encode-once fan-out).

    The shared :class:`~mysticeti_tpu.synchronizer.FrameCache` hands the
    SAME EncodedFrame object to every subscriber at one cursor; the TCP
    ``write_loop`` ships ``payload`` without re-encoding, while the
    simulated network delivers ``message`` object-identically and never
    pays for serialization at all (``payload`` is built lazily on first
    wire access).  ``payload`` is byte-identical to
    ``encode_message(message)`` — pinned by the golden-corpus test."""

    __slots__ = ("message", "_payload")

    def __init__(self, message: NetworkMessage, payload: Optional[bytes] = None) -> None:
        self.message = message
        self._payload = payload

    @property
    def payload(self) -> bytes:
        if self._payload is None:
            self._payload = encode_message(self.message)
        return self._payload


def frame_payload(msg: NetworkMessage) -> bytes:
    """The wire payload for a queued message: the cached bytes of an
    :class:`EncodedFrame`, a fresh encode for everything else."""
    if type(msg) is EncodedFrame:
        return msg.payload
    return encode_message(msg)


class _SendQueue(asyncio.Queue):
    """Bounded send queue with a capped urgent lane.

    ``put_front_nowait`` enqueues ahead of everything already queued and
    ignores the bulk bound — reserved for Ping/Pong, so an RTT probe can
    never sit behind a saturated bulk backlog inflating the latency
    estimate into the 5 s breaker (the snapshot-stream false-trip).  The
    lane has its OWN small cap: the echo path answers every received Ping
    with a Pong, and without a bound a peer flooding Pings while refusing
    to read would grow the deque without limit (the old per-message path
    backpressured via the full queue).  Legitimate traffic is one probe
    per ``PING_INTERVAL_S`` plus its echo — nowhere near the cap; over it,
    the probe is dropped, which the protocol tolerates by design.
    Mirrors ``put_nowait`` on the documented-stable asyncio.Queue
    internals (``_queue`` deque + getter wakeup)."""

    URGENT_CAP = 16

    def _init(self, maxsize: int) -> None:
        super()._init(maxsize)
        self.urgent_queued = 0

    def _get(self):
        item = self._queue.popleft()
        if type(item) is Ping or type(item) is Pong:
            self.urgent_queued -= 1
        return item

    def put_front_nowait(self, item) -> bool:
        if self.urgent_queued >= self.URGENT_CAP:
            return False
        self.urgent_queued += 1
        self._queue.appendleft(item)
        self._unfinished_tasks += 1
        self._finished.clear()
        self._wakeup_next(self._getters)
        return True


def _is_urgent(msg: NetworkMessage) -> bool:
    return type(msg) is Ping or type(msg) is Pong


class Connection:
    """One live peer link: outgoing via ``send``, incoming via ``receiver``.

    The transport (TCP worker or simulated link) feeds ``receiver`` and drains
    the internal send queue; when either side drops, the connection closes and
    the owning worker establishes a fresh Connection object (network.rs:195-242
    Worker semantics).
    """

    def __init__(self, peer: int, latency_getter=None, metrics=None) -> None:
        self.peer = peer
        self.sender: asyncio.Queue = _SendQueue(maxsize=1024)
        self.receiver: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._closed = asyncio.Event()
        self._latency_getter = latency_getter
        self.metrics = metrics

    def try_send(self, msg: NetworkMessage) -> bool:
        """Non-blocking send; drops (returns False) when the peer is slow —
        the reference's bounded-channel backpressure behavior.  Drops are
        counted on ``connection_send_drops_total{peer}`` (they were silent:
        a fleet losing fetch requests to backpressure looked identical to
        one that never sent them)."""
        if self.is_closed():
            return False
        if _is_urgent(msg):
            if self.sender.put_front_nowait(msg):
                return True
            self._count_drop()
            return False
        try:
            self.sender.put_nowait(msg)
            return True
        except asyncio.QueueFull:
            self._count_drop()
            return False

    def _count_drop(self) -> None:
        if self.metrics is not None:
            self.metrics.connection_send_drops_total.labels(
                str(self.peer)
            ).inc()

    async def send(self, msg: NetworkMessage) -> None:
        if self.is_closed():
            return
        if _is_urgent(msg):
            # Ping/Pong jump the queue AND never block behind a full one —
            # a saturated bulk stream must not delay (or deadlock) the RTT
            # probe that decides whether this link is healthy.  Beyond the
            # urgent-lane cap (a ping flood) the probe is dropped, never
            # queued unboundedly.
            if not self.sender.put_front_nowait(msg):
                self._count_drop()
            return
        await self.sender.put(msg)

    async def recv(self) -> Optional[NetworkMessage]:
        get = asyncio.ensure_future(self.receiver.get())
        closed = asyncio.ensure_future(self._closed.wait())
        try:
            done, pending = await asyncio.wait(
                {get, closed}, return_when=asyncio.FIRST_COMPLETED
            )
        except asyncio.CancelledError:
            # A connection task torn down mid-recv (node crash/stop) must not
            # orphan the two helper tasks — they would linger pending until
            # loop close ("Task was destroyed but it is pending").
            get.cancel()
            closed.cancel()
            raise
        for p in pending:
            p.cancel()
        if get in done:
            return self._unwrap(get.result())
        # Drain anything already delivered before reporting closure.
        try:
            return self._unwrap(self.receiver.get_nowait())
        except asyncio.QueueEmpty:
            return None

    @staticmethod
    def _unwrap(msg):
        """Simulated links deliver the disseminator's EncodedFrame objects
        verbatim (no serialization in-process); consumers see the message,
        keeping the sim a drop-in for the TCP transport."""
        if type(msg) is EncodedFrame:
            return msg.message
        return msg

    def latency(self) -> float:
        """Smoothed RTT estimate in seconds (inf until first pong)."""
        if self._latency_getter is not None:
            return self._latency_getter()
        return float("inf")

    def close(self) -> None:
        self._closed.set()

    def is_closed(self) -> bool:
        return self._closed.is_set()


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "little")
    if length > MAX_FRAME:
        raise SerdeError(f"frame of {length} bytes exceeds MAX_FRAME")
    return await reader.readexactly(length)


def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(len(payload).to_bytes(4, "little") + payload)


class _FrameReceiver(asyncio.BufferedProtocol):
    """Zero-copy mesh frame receiver: ``recv_into`` a reusable buffer.

    After the stream handshake the connection's transport is switched onto
    this protocol (``transport.set_protocol`` retargets the selector's
    read-ready path to ``get_buffer``/``buffer_updated``): the event loop
    then ``recv_into``s DIRECTLY into the per-connection assembly buffer —
    no StreamReader ``feed_data`` append copy, no ``readexactly`` join
    copy.  ``read_frame`` yields complete frames as memoryviews over the
    buffer; ``decode_message`` turns block payloads into sub-views and
    ``StatementBlock.from_bytes`` materializes exactly one ``bytes`` per
    block for the canonical cache, so a disseminated block's bytes are
    copied once between the kernel and the DAG.

    Buffer lifecycle: the assembly buffer is reused across frames.  When
    compaction or growth would disturb a frame view still alive downstream
    (deep receive pipelining holds decoded-but-unconsumed frames), the
    unparsed tail moves to a FRESH buffer and the old one is left to the
    GC with its views — detected by refcount: the buffer has exactly two
    references (the attribute + the check's argument) when no view is
    exported.  Views never outlive their backing store.

    Division of labor with the streams machinery: the WRITE half stays on
    the original ``StreamWriter``/``StreamReaderProtocol`` — pause/resume
    and connection_lost are forwarded so ``writer.drain()`` keeps its flow
    -control contract.  READ-side backpressure is ours: parsed-but-unread
    frames beyond ``MAX_BUFFERED_FRAMES`` pause the transport until
    ``read_frame`` drains them (the old path got the same effect from the
    StreamReader high-water mark).
    """

    MIN_BUF = 64 * 1024
    MAX_BUFFERED_FRAMES = 64

    def __init__(self, stream_protocol, transport) -> None:
        self._stream_protocol = stream_protocol
        self._transport = transport
        self._buf = bytearray(self.MIN_BUF)
        self._start = 0  # offset of the first unparsed byte
        self._have = 0  # offset one past the last filled byte
        self._frames: collections.deque = collections.deque()
        self._waiter: Optional[asyncio.Future] = None
        self._exc: Optional[BaseException] = None
        self._eof = False
        self._paused = False
        # True between get_buffer and the matching buffer_updated: the
        # event loop holds a view of _buf for an in-flight recv.  On the
        # selector loop the pair is synchronous, but a proactor loop keeps
        # the view across the overlapped recv — swapping _buf then would
        # send incoming bytes into the orphaned buffer.
        self._recv_pending = False

    @classmethod
    def attach(cls, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        """Switch a handshaken stream connection to zero-copy reads.

        Returns None when the transport cannot be switched (mock streams in
        tests, ``MYSTICETI_MESH_LEGACY=1``) — the caller falls back to the
        ``_read_frame(reader)`` stream path, frame-for-frame compatible."""
        if mesh_legacy():
            return None
        transport = getattr(writer, "transport", None)
        buffered = getattr(reader, "_buffer", None)
        if (
            transport is None
            or not isinstance(buffered, bytearray)
            or not hasattr(transport, "set_protocol")
            or not hasattr(transport, "get_protocol")
        ):
            return None
        try:
            receiver = cls(transport.get_protocol(), transport)
            # Switch FIRST: if the transport refuses (base-class stub, a
            # wrapper), the StreamReader's buffer is untouched and the
            # stream fallback stays whole.  The switch and the drain below
            # run in one synchronous step, so no data callback can land
            # between them.
            transport.set_protocol(receiver)
        except (AttributeError, NotImplementedError):
            return None
        # Bytes the stream consumed off the socket between the handshake
        # and the switch belong to us now — seed the assembly buffer so
        # nothing is lost or read twice.
        if buffered:
            receiver._reserve(len(buffered))
            receiver._buf[: len(buffered)] = buffered
            receiver._have = len(buffered)
            del buffered[:]
            receiver._parse()
        if not receiver._paused:
            # The StreamReader may have paused the transport itself (a
            # handshake-window burst past 2x its limit); its pause is not
            # ours and nothing else would ever resume it — the read side
            # would stall forever while pings keep flowing out.
            try:
                transport.resume_reading()
            except Exception:  # noqa: BLE001 - not paused / closing: fine
                pass
        return receiver

    # -- consumer side --

    async def read_frame(self) -> memoryview:
        """Next complete frame payload (header stripped) as a memoryview.

        Raises ``IncompleteReadError`` on EOF and the stored exception on
        transport error — the same failure surface ``_read_frame`` has."""
        while not self._frames:
            if self._exc is not None:
                raise self._exc
            if self._eof:
                raise asyncio.IncompleteReadError(b"", 4)
            self._waiter = asyncio.get_event_loop().create_future()
            try:
                await self._waiter
            finally:
                self._waiter = None
        frame = self._frames.popleft()
        if (
            not self._frames
            and self._start == self._have
            and len(self._buf) > 8 * self.MIN_BUF
            and not self._recv_pending
        ):
            # A past jumbo frame grew the assembly buffer; once the backlog
            # fully clears, swap in a fresh small one — a 50-peer node
            # would otherwise pin one jumbo buffer per connection forever.
            # Always safe: live downstream views (including the frame just
            # popped) keep the OLD buffer alive; we only stop writing to it.
            self._buf = bytearray(self.MIN_BUF)
            self._start = self._have = 0
        if self._paused and len(self._frames) <= self.MAX_BUFFERED_FRAMES // 2:
            self._paused = False
            try:
                self._transport.resume_reading()
            except Exception:  # noqa: BLE001 - transport already gone
                pass
        return frame

    # -- BufferedProtocol callbacks (event-loop thread) --

    def get_buffer(self, sizehint: int) -> memoryview:
        tail = self._have - self._start
        need = 4096
        if tail >= 4:
            # A partial frame is pending: reserve enough for its remainder
            # so large frames assemble without quadratic regrowth.  An
            # over-MAX length is not our problem here — _parse rejects it.
            length = int.from_bytes(
                self._buf[self._start : self._start + 4], "little"
            )
            if length <= MAX_FRAME:
                need = max(need, 4 + length - tail)
        if len(self._buf) - self._have < need:
            self._reserve(need)
        self._recv_pending = True
        return memoryview(self._buf)[self._have :]

    def buffer_updated(self, nbytes: int) -> None:
        self._recv_pending = False
        self._have += nbytes
        self._parse()

    def eof_received(self) -> bool:
        self._eof = True
        self._wake()
        return False  # a half-closed mesh peer is a dead peer: close

    def connection_lost(self, exc: Optional[BaseException]) -> None:
        self._recv_pending = False
        if exc is not None:
            self._exc = exc
        self._eof = True
        self._wake()
        # The write half (StreamWriter.drain / wait_closed) still lives on
        # the original protocol: it must observe the loss.
        self._stream_protocol.connection_lost(exc)

    def pause_writing(self) -> None:
        self._stream_protocol.pause_writing()

    def resume_writing(self) -> None:
        self._stream_protocol.resume_writing()

    # -- internals --

    def _wake(self) -> None:
        if self._waiter is not None and not self._waiter.done():
            self._waiter.set_result(None)

    def _views_exported(self) -> bool:
        # Two references = self._buf + getrefcount's argument; anything
        # beyond that is a parsed frame view (queued here or held by a
        # consumer downstream).
        return sys.getrefcount(self._buf) > 2 or bool(self._frames)

    def _reserve(self, need: int) -> None:
        """Ensure ``need`` writable bytes after ``_have``, compacting the
        unparsed tail to offset 0 (into a fresh buffer if live views pin
        the current one)."""
        tail = self._have - self._start
        cap = len(self._buf)
        want = tail + need
        if want > cap:
            cap = max(self.MIN_BUF, 1 << (want - 1).bit_length())
        if cap != len(self._buf) or self._views_exported():
            new = bytearray(cap)
            new[:tail] = memoryview(self._buf)[self._start : self._have]
            self._buf = new
        elif self._start:
            self._buf[:tail] = self._buf[self._start : self._have]
        self._start, self._have = 0, tail

    def _parse(self) -> None:
        if _native_split_frames is not None:
            # Native batch split: one call walks the whole assembly buffer
            # and returns every complete frame's (offset, length) span; only
            # the memoryview wrapping — the step that must touch Python
            # objects — stays here.  All slices share one managed buffer,
            # which keeps the `_views_exported` refcount probe truthful
            # (any live slice pins the bytearray's refcount above 2).
            spans, start, oversized = _native_split_frames(
                self._buf, self._start, self._have, MAX_FRAME
            )
            if oversized:
                self._exc = SerdeError(
                    f"frame of {oversized} bytes exceeds MAX_FRAME"
                )
                self._wake()
                self._transport.close()
                return
            if spans:
                view = memoryview(self._buf)
                for off, length in spans:
                    self._frames.append(view[off : off + length])
            self._start = start
        else:
            buf, start, have = self._buf, self._start, self._have
            while have - start >= 4:
                length = int.from_bytes(buf[start : start + 4], "little")
                if length > MAX_FRAME:
                    self._exc = SerdeError(
                        f"frame of {length} bytes exceeds MAX_FRAME"
                    )
                    self._wake()
                    self._transport.close()
                    return
                end = start + 4 + length
                if end > have:
                    break
                self._frames.append(memoryview(buf)[start + 4 : end])
                start = end
            self._start = start
        if self._frames:
            self._wake()
            if (
                len(self._frames) > self.MAX_BUFFERED_FRAMES
                and not self._paused
            ):
                self._paused = True
                try:
                    self._transport.pause_reading()
                except Exception:  # noqa: BLE001 - transport already gone
                    pass


class TcpNetwork:
    """Full-mesh TCP among the committee (network.rs:48-292).

    ``connections`` is an asyncio.Queue of fresh Connection objects handed to
    the node orchestration (net_sync.rs consumes them identically).
    """

    def __init__(
        self,
        authority: int,
        addresses: List[Tuple[str, int]],
        metrics=None,
        max_latency_s: float = 5.0,
    ) -> None:
        self.authority = authority
        self.addresses = addresses
        self.connections: asyncio.Queue = asyncio.Queue()
        self.metrics = metrics
        self.max_latency_s = max_latency_s
        self._latency: Dict[int, float] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._tasks: List[asyncio.Task] = []
        self._stopped = False

    @classmethod
    async def start(cls, authority, addresses, metrics=None, **kwargs) -> "TcpNetwork":
        net = cls(authority, addresses, metrics, **kwargs)
        host, port = addresses[authority]
        net._server = await asyncio.start_server(
            net._handle_inbound, host="0.0.0.0", port=port
        )
        # Dial every higher-index peer; lower-index peers dial us.
        for peer in range(len(addresses)):
            if peer > authority:
                net._tasks.append(
                    spawn_logged(net._dial_worker(peer), log, name=f"dial {peer}")
                )
        return net

    # -- inbound --

    async def _handle_inbound(self, reader, writer) -> None:
        try:
            hello = await asyncio.wait_for(reader.readexactly(12), timeout=5.0)
            magic = int.from_bytes(hello[:4], "little")
            peer = int.from_bytes(hello[4:], "little")
            if magic != HANDSHAKE_MAGIC or peer >= len(self.addresses):
                writer.close()
                return
            _write_frame(
                writer,
                HANDSHAKE_MAGIC.to_bytes(4, "little")
                + self.authority.to_bytes(8, "little"),
            )
            await writer.drain()
        except Exception:
            writer.close()
            return
        await self._run_peer(peer, reader, writer)

    # -- outbound --

    async def _dial_worker(self, peer: int) -> None:
        """Reconnect-forever loop (network.rs:218-242), with seeded jitter on
        the backoff (the simulator's loop RNG when present, else a
        per-(dialer, peer) seed) so fleet-wide reconnect storms decorrelate."""
        rng = getattr(asyncio.get_event_loop(), "rng", None) or random.Random(
            (self.authority << 20) ^ peer
        )
        delay = 0.1
        while not self._stopped:
            try:
                host, port = self.addresses[peer]
                reader, writer = await asyncio.open_connection(host, port)
                writer.write(
                    HANDSHAKE_MAGIC.to_bytes(4, "little")
                    + self.authority.to_bytes(8, "little")
                )
                await writer.drain()
                ack = await asyncio.wait_for(_read_frame(reader), timeout=5.0)
                if (
                    int.from_bytes(ack[:4], "little") != HANDSHAKE_MAGIC
                    or int.from_bytes(ack[4:], "little") != peer
                ):
                    raise ConnectionError("bad handshake ack")
                delay = 0.1
                log.debug("dialed authority %d", peer)
                await self._run_peer(peer, reader, writer)
            except (OSError, asyncio.IncompleteReadError, ConnectionError, SerdeError,
                    asyncio.TimeoutError) as exc:
                log.debug("dial to authority %d failed: %r (retrying)", peer, exc)
            await asyncio.sleep(jittered_backoff(delay, rng))
            delay = min(delay * 2, 5.0)

    # -- shared read/write/ping loops --

    async def _run_peer(self, peer: int, reader, writer) -> None:
        conn = Connection(
            peer,
            latency_getter=lambda p=peer: self._latency.get(p, float("inf")),
            metrics=self.metrics,
        )
        await self.connections.put(conn)
        legacy = mesh_legacy()
        receiver = None if legacy else _FrameReceiver.attach(reader, writer)
        metrics = self.metrics
        recv_bytes = sent_bytes = coalesced = None
        if metrics is not None and not legacy:
            recv_bytes = metrics.mesh_wire_bytes_total.labels("received")
            sent_bytes = metrics.mesh_wire_bytes_total.labels("sent")
            coalesced = metrics.mesh_frames_coalesced_total

        def _count_malformed() -> None:
            if metrics is not None:
                metrics.mysticeti_malformed_frames_total.labels(
                    str(peer)
                ).inc()

        async def read_loop():
            while True:
                try:
                    if receiver is not None:
                        frame = await receiver.read_frame()
                    else:
                        frame = await _read_frame(reader)
                except SerdeError as exc:
                    # Garbage or oversized length prefix: the stream is
                    # desynced beyond recovery — sever THIS connection
                    # (counted, attributed) and let the reconnect worker
                    # start clean.  That is the cap on malformed-frame
                    # handling: one bad frame, one severed connection,
                    # never an uncaught decode error.
                    log.warning(
                        "malformed frame from authority %d (%s): severing "
                        "connection", peer, exc,
                    )
                    _count_malformed()
                    return
                if recv_bytes is not None:
                    recv_bytes.inc(len(frame) + 4)
                try:
                    msg = decode_message(frame)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 - byzantine payload
                    # Undecodable payload inside a well-framed length: same
                    # verdict as a garbage prefix.  Catching broadly is the
                    # contract — no struct/decode error may escape the
                    # protocol callback path.
                    log.warning(
                        "undecodable frame payload from authority %d (%r): "
                        "severing connection", peer, exc,
                    )
                    _count_malformed()
                    return
                if isinstance(msg, Ping):
                    # Priority lane: the echo must not queue behind bulk
                    # frames or the peer's RTT estimate absorbs our send
                    # backlog (Connection.send front-queues Ping/Pong).
                    await conn.send(Pong(msg.nanos))
                    continue
                if isinstance(msg, Pong):
                    rtt = (time.monotonic_ns() - msg.nanos) / 1e9
                    prev = self._latency.get(peer)
                    self._latency[peer] = rtt if prev is None else 0.8 * prev + 0.2 * rtt
                    if self.metrics is not None:
                        self.metrics.connection_latency.labels(str(peer)).observe(rtt)
                    if rtt >= self.max_latency_s:
                        log.warning(
                            "latency breaker: authority %d RTT %.2fs >= %.2fs",
                            peer, rtt, self.max_latency_s,
                        )
                        raise ConnectionError("latency breaker tripped")
                    continue
                await conn.receiver.put(msg)

        async def write_loop():
            import contextlib

            encode_timer = (
                metrics.utilization_timer
                if metrics is not None
                else (lambda _name: contextlib.nullcontext())
            )
            if legacy:
                # Pre-r10 path: one encode + one concat + one drain PER
                # frame.  The encode timer runs here too so the A/B
                # artifact can compare mesh encode CPU across modes.
                while True:
                    msg = await conn.sender.get()
                    with encode_timer("net:mesh_encode"):
                        payload = frame_payload(msg)
                    _write_frame(writer, payload)
                    await writer.drain()
            while True:
                # Scatter-gather coalescing: drain the queue non-blocking
                # and ship the batch as one writelines + ONE drain — the
                # per-frame header+payload concat and per-frame drain were
                # a measurable share of mesh send CPU at load.  The batch
                # is byte-capped: the old per-frame drain throttled the
                # transport buffer one frame at a time, and an unbounded
                # drain of a deep queue of multi-MB frames would buffer
                # them ALL before the flow-control await.
                msg = await conn.sender.get()
                urgent_parts: List[bytes] = []
                parts: List[bytes] = []
                total = 0
                count = 0
                with encode_timer("net:mesh_encode"):
                    while True:
                        payload = frame_payload(msg)
                        # Ping/Pong lead the writelines batch (never behind
                        # bulk payloads); headers are fresh immutable
                        # objects per write (the PR 5 transport-buffer
                        # lesson: a 3.12+ transport may hold frame N
                        # zero-copy in its buffer while N+1 is built).
                        dest = urgent_parts if _is_urgent(msg) else parts
                        dest.append(len(payload).to_bytes(4, "little"))
                        dest.append(payload)
                        total += 4 + len(payload)
                        count += 1
                        if total >= MAX_COALESCE_BYTES:
                            break
                        try:
                            msg = conn.sender.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                writer.writelines(urgent_parts + parts)
                if sent_bytes is not None:
                    sent_bytes.inc(total)
                if coalesced is not None and count > 1:
                    coalesced.inc(count - 1)
                await writer.drain()

        async def ping_loop():
            while True:
                await conn.send(Ping(time.monotonic_ns()))
                await asyncio.sleep(PING_INTERVAL_S)

        tasks = [
            asyncio.ensure_future(read_loop()),
            asyncio.ensure_future(write_loop()),
            asyncio.ensure_future(ping_loop()),
        ]
        try:
            done, pending = await asyncio.wait(tasks, return_when=asyncio.FIRST_COMPLETED)
        finally:
            for t in tasks:
                t.cancel()
            conn.close()
            writer.close()

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


# Native data-plane wiring (mirrors types.py's decoder gate): resolve the
# batched frame helpers once, behind the `native is None` fallback contract
# the native-fallback lint rule enforces.  Each alias is None when the
# extension (or the specific function — build skew) is absent, and every
# call site above branches on that.
from .native import native as _native_mod  # noqa: E402

_NATIVE_PARSE_TAGS = frozenset(
    (_MSG_BLOCKS, _MSG_RESPONSE, _MSG_BLOCKS_TIMESTAMPED)
)
_native_encode_frame = None
_native_parse_spans = None
_native_split_frames = None
if _native_mod is not None:
    if hasattr(_native_mod, "encode_blocks_frame"):
        _native_encode_frame = _native_mod.encode_blocks_frame
    if hasattr(_native_mod, "parse_blocks_spans"):
        _native_parse_spans = _native_mod.parse_blocks_spans
    if hasattr(_native_mod, "split_frames"):
        _native_split_frames = _native_mod.split_frames
