"""Validator mesh networking: wire protocol, framing, TCP transport, RTT probes.

Capability parity with ``mysticeti-core/src/network.rs``:

* ``NetworkMessage`` taxonomy {SubscribeOwnFrom, Blocks, RequestBlocks,
  RequestBlocksResponse, BlockNotFound} (network.rs:35-46) + embedded
  Ping/Pong RTT probe (network.rs:33,324-406,563-574)
* 4-byte length-prefixed frames, 16 MiB cap (network.rs:216,397-459)
* handshake magic + authority-index exchange (network.rs:214-217,244-292)
* per-peer reconnect-forever workers (network.rs:218-242)
* per-peer RTT estimate feeding the latency-weighted fetcher and the
  max-latency connection breaker (network.rs:378-381)

Transport design difference (documented, not accidental): the reference races
active+passive connections per peer; here the lower authority index dials and
the higher accepts — same full-mesh + reconnect capability with half the
connection-management states.  ``Connection`` is a pair of asyncio queues, so
the simulated network (simulated_network.py) is a drop-in replacement.
"""
from __future__ import annotations

import asyncio
import dataclasses
import random
import time
from typing import Dict, List, Optional, Tuple

from .serde import Reader, SerdeError, Writer
from .tracing import logger
from .utils.tasks import spawn_logged
from .types import BlockReference, RoundNumber, StatementBlock

log = logger(__name__)

HANDSHAKE_MAGIC = 0x7C9A_11B7
MAX_FRAME = 16 * 1024 * 1024
PING_INTERVAL_S = 30.0

def jittered_backoff(delay: float, rng: random.Random) -> float:
    """Uniform [0.5, 1.5)x jitter around an exponential-backoff delay.

    A bare doubling schedule synchronizes reconnect storms: every dialer that
    lost the same peer at the same moment retries on the same beat, hammering
    the recovering node in lockstep bursts.  The multiplicative jitter keeps
    the expected delay while decorrelating the fleet; callers pass a SEEDED
    rng so simulated runs stay reproducible.
    """
    return delay * (0.5 + rng.random())


_MSG_SUBSCRIBE = 1
_MSG_BLOCKS = 2
_MSG_REQUEST = 3
_MSG_RESPONSE = 4
_MSG_NOT_FOUND = 5
_MSG_PING = 6
_MSG_PONG = 7
_MSG_SUBSCRIBE_OTHERS = 8
_MSG_REQUEST_SNAPSHOT = 9
_MSG_SNAPSHOT = 10
_MSG_REQUEST_SNAPSHOT_STREAM = 11
_MSG_BLOCKS_TIMESTAMPED = 12


@dataclasses.dataclass(frozen=True)
class SubscribeOwnFrom:
    round: RoundNumber


@dataclasses.dataclass(frozen=True)
class SubscribeOthersFrom:
    """Helper-stream request (synchronizer.rs:169-205's dormant
    ``disseminate_others_blocks``, made live behind a Parameters knob):
    "relay AUTHORITY's blocks you hold, from this round on" — sent to a
    helper peer when the authority itself is unreachable.  A soft wire
    extension per docs/wire-format.md §7: receivers that predate the tag
    reset the connection, so senders only emit it when the knob is on."""

    authority: int
    round: RoundNumber


@dataclasses.dataclass(frozen=True)
class RequestSnapshot:
    """Snapshot catch-up ask (storage.py): "my committed height is
    ``commit_height``; if I am far behind, send me your commit baseline".
    A soft wire extension per docs/wire-format.md §7 — only sent when
    ``StorageParameters.snapshot_catchup`` is on; receivers that predate
    the tag reset the connection."""

    commit_height: int


@dataclasses.dataclass(frozen=True)
class SnapshotResponse:
    """The serving node's :class:`~mysticeti_tpu.storage.SnapshotManifest`
    (opaque canonical bytes).  The block window itself is only shipped on an
    explicit :class:`RequestSnapshotStream` — every qualifying peer answers
    the ask with a manifest (cheap), but the receiver adopts exactly one and
    pulls the bulk window from that peer alone."""

    manifest: bytes


@dataclasses.dataclass(frozen=True)
class RequestSnapshotStream:
    """Post-adoption bulk ask: "stream me every block you hold from
    ``from_round`` up" — sent to the ONE peer whose manifest was adopted;
    the window arrives as ordinary ``Blocks`` frames, decoded and re-hashed
    by the receiver like any push stream."""

    from_round: int


@dataclasses.dataclass(frozen=True)
class Blocks:
    blocks: Tuple[bytes, ...]  # serialized StatementBlocks (zero re-encode)


@dataclasses.dataclass(frozen=True)
class TimestampedBlocks(Blocks):
    """A ``Blocks`` push frame stamped with the sender's clocks at send time
    (fleet causal tracing, tools/fleet_trace.py): ``sent_monotonic_ns`` is
    the sender's runtime clock (detects wall-clock jumps between frames),
    ``sent_wall_ns`` its wall clock — the receiver's arrival time minus it
    is the RAW per-link transit the skew estimator aligns.  A soft wire
    extension per docs/wire-format.md §7 (tag 12): receivers that predate
    the tag reset the connection, so senders only emit it when
    ``SynchronizerParameters.timestamp_frames`` is on.  Subclasses
    ``Blocks`` so every receive path handles it unchanged."""

    sent_monotonic_ns: int = 0
    sent_wall_ns: int = 0


def wall_jump_us(prev: Tuple[int, int], cur: Tuple[int, int]) -> int:
    """|Δwall − Δmonotonic| between two consecutive sender stamp pairs
    ``(sent_monotonic_ns, sent_wall_ns)``, in microseconds.

    Between frames both sender clocks advance by real elapsed time, so the
    two deltas agree to within slew; a large disagreement means the
    sender's WALL clock stepped (NTP jump) between the frames — the
    receiver must discard that frame's wall-derived transit sample, which
    is the reason the monotonic stamp rides the wire at all."""
    dw = cur[1] - prev[1]
    dm = cur[0] - prev[0]
    return abs(dw - dm) // 1000


@dataclasses.dataclass(frozen=True)
class RequestBlocks:
    references: Tuple[BlockReference, ...]


@dataclasses.dataclass(frozen=True)
class RequestBlocksResponse:
    blocks: Tuple[bytes, ...]


@dataclasses.dataclass(frozen=True)
class BlockNotFound:
    references: Tuple[BlockReference, ...]


@dataclasses.dataclass(frozen=True)
class Ping:
    nanos: int


@dataclasses.dataclass(frozen=True)
class Pong:
    nanos: int


NetworkMessage = object


def encode_message(msg: NetworkMessage) -> bytes:
    w = Writer()
    if isinstance(msg, SubscribeOwnFrom):
        w.u8(_MSG_SUBSCRIBE).u64(msg.round)
    elif isinstance(msg, SubscribeOthersFrom):
        w.u8(_MSG_SUBSCRIBE_OTHERS).u64(msg.authority).u64(msg.round)
    elif isinstance(msg, TimestampedBlocks):
        # Before the Blocks branch: a TimestampedBlocks IS a Blocks.
        w.u8(_MSG_BLOCKS_TIMESTAMPED)
        w.u64(msg.sent_monotonic_ns).u64(msg.sent_wall_ns)
        w.u32(len(msg.blocks))
        for b in msg.blocks:
            w.bytes(b)
    elif isinstance(msg, Blocks):
        w.u8(_MSG_BLOCKS).u32(len(msg.blocks))
        for b in msg.blocks:
            w.bytes(b)
    elif isinstance(msg, RequestBlocks):
        w.u8(_MSG_REQUEST).u32(len(msg.references))
        for r in msg.references:
            r.encode(w)
    elif isinstance(msg, RequestBlocksResponse):
        w.u8(_MSG_RESPONSE).u32(len(msg.blocks))
        for b in msg.blocks:
            w.bytes(b)
    elif isinstance(msg, BlockNotFound):
        w.u8(_MSG_NOT_FOUND).u32(len(msg.references))
        for r in msg.references:
            r.encode(w)
    elif isinstance(msg, Ping):
        w.u8(_MSG_PING).u64(msg.nanos)
    elif isinstance(msg, Pong):
        w.u8(_MSG_PONG).u64(msg.nanos)
    elif isinstance(msg, RequestSnapshot):
        w.u8(_MSG_REQUEST_SNAPSHOT).u64(msg.commit_height)
    elif isinstance(msg, SnapshotResponse):
        w.u8(_MSG_SNAPSHOT).bytes(msg.manifest)
    elif isinstance(msg, RequestSnapshotStream):
        w.u8(_MSG_REQUEST_SNAPSHOT_STREAM).u64(msg.from_round)
    else:  # pragma: no cover
        raise SerdeError(f"unknown message {type(msg)}")
    return w.finish()


def decode_message(data: bytes) -> NetworkMessage:
    r = Reader(data)
    tag = r.u8()
    if tag == _MSG_SUBSCRIBE:
        msg: NetworkMessage = SubscribeOwnFrom(r.u64())
    elif tag == _MSG_SUBSCRIBE_OTHERS:
        msg = SubscribeOthersFrom(r.u64(), r.u64())
    elif tag == _MSG_BLOCKS:
        msg = Blocks(tuple(r.bytes() for _ in range(r.u32())))
    elif tag == _MSG_REQUEST:
        msg = RequestBlocks(tuple(BlockReference.decode(r) for _ in range(r.u32())))
    elif tag == _MSG_RESPONSE:
        msg = RequestBlocksResponse(tuple(r.bytes() for _ in range(r.u32())))
    elif tag == _MSG_NOT_FOUND:
        msg = BlockNotFound(tuple(BlockReference.decode(r) for _ in range(r.u32())))
    elif tag == _MSG_PING:
        msg = Ping(r.u64())
    elif tag == _MSG_PONG:
        msg = Pong(r.u64())
    elif tag == _MSG_REQUEST_SNAPSHOT:
        msg = RequestSnapshot(r.u64())
    elif tag == _MSG_SNAPSHOT:
        msg = SnapshotResponse(r.bytes())
    elif tag == _MSG_REQUEST_SNAPSHOT_STREAM:
        msg = RequestSnapshotStream(r.u64())
    elif tag == _MSG_BLOCKS_TIMESTAMPED:
        monotonic_ns, wall_ns = r.u64(), r.u64()
        msg = TimestampedBlocks(
            tuple(r.bytes() for _ in range(r.u32())),
            sent_monotonic_ns=monotonic_ns,
            sent_wall_ns=wall_ns,
        )
    else:
        raise SerdeError(f"unknown message tag {tag}")
    r.expect_done()
    return msg


class Connection:
    """One live peer link: outgoing via ``send``, incoming via ``receiver``.

    The transport (TCP worker or simulated link) feeds ``receiver`` and drains
    the internal send queue; when either side drops, the connection closes and
    the owning worker establishes a fresh Connection object (network.rs:195-242
    Worker semantics).
    """

    def __init__(self, peer: int, latency_getter=None) -> None:
        self.peer = peer
        self.sender: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self.receiver: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._closed = asyncio.Event()
        self._latency_getter = latency_getter

    def try_send(self, msg: NetworkMessage) -> bool:
        """Non-blocking send; drops (returns False) when the peer is slow —
        the reference's bounded-channel backpressure behavior."""
        if self.is_closed():
            return False
        try:
            self.sender.put_nowait(msg)
            return True
        except asyncio.QueueFull:
            return False

    async def send(self, msg: NetworkMessage) -> None:
        if self.is_closed():
            return
        await self.sender.put(msg)

    async def recv(self) -> Optional[NetworkMessage]:
        get = asyncio.ensure_future(self.receiver.get())
        closed = asyncio.ensure_future(self._closed.wait())
        try:
            done, pending = await asyncio.wait(
                {get, closed}, return_when=asyncio.FIRST_COMPLETED
            )
        except asyncio.CancelledError:
            # A connection task torn down mid-recv (node crash/stop) must not
            # orphan the two helper tasks — they would linger pending until
            # loop close ("Task was destroyed but it is pending").
            get.cancel()
            closed.cancel()
            raise
        for p in pending:
            p.cancel()
        if get in done:
            return get.result()
        # Drain anything already delivered before reporting closure.
        try:
            return self.receiver.get_nowait()
        except asyncio.QueueEmpty:
            return None

    def latency(self) -> float:
        """Smoothed RTT estimate in seconds (inf until first pong)."""
        if self._latency_getter is not None:
            return self._latency_getter()
        return float("inf")

    def close(self) -> None:
        self._closed.set()

    def is_closed(self) -> bool:
        return self._closed.is_set()


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "little")
    if length > MAX_FRAME:
        raise SerdeError(f"frame of {length} bytes exceeds MAX_FRAME")
    return await reader.readexactly(length)


def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(len(payload).to_bytes(4, "little") + payload)


class TcpNetwork:
    """Full-mesh TCP among the committee (network.rs:48-292).

    ``connections`` is an asyncio.Queue of fresh Connection objects handed to
    the node orchestration (net_sync.rs consumes them identically).
    """

    def __init__(
        self,
        authority: int,
        addresses: List[Tuple[str, int]],
        metrics=None,
        max_latency_s: float = 5.0,
    ) -> None:
        self.authority = authority
        self.addresses = addresses
        self.connections: asyncio.Queue = asyncio.Queue()
        self.metrics = metrics
        self.max_latency_s = max_latency_s
        self._latency: Dict[int, float] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._tasks: List[asyncio.Task] = []
        self._stopped = False

    @classmethod
    async def start(cls, authority, addresses, metrics=None, **kwargs) -> "TcpNetwork":
        net = cls(authority, addresses, metrics, **kwargs)
        host, port = addresses[authority]
        net._server = await asyncio.start_server(
            net._handle_inbound, host="0.0.0.0", port=port
        )
        # Dial every higher-index peer; lower-index peers dial us.
        for peer in range(len(addresses)):
            if peer > authority:
                net._tasks.append(
                    spawn_logged(net._dial_worker(peer), log, name=f"dial {peer}")
                )
        return net

    # -- inbound --

    async def _handle_inbound(self, reader, writer) -> None:
        try:
            hello = await asyncio.wait_for(reader.readexactly(12), timeout=5.0)
            magic = int.from_bytes(hello[:4], "little")
            peer = int.from_bytes(hello[4:], "little")
            if magic != HANDSHAKE_MAGIC or peer >= len(self.addresses):
                writer.close()
                return
            _write_frame(
                writer,
                HANDSHAKE_MAGIC.to_bytes(4, "little")
                + self.authority.to_bytes(8, "little"),
            )
            await writer.drain()
        except Exception:
            writer.close()
            return
        await self._run_peer(peer, reader, writer)

    # -- outbound --

    async def _dial_worker(self, peer: int) -> None:
        """Reconnect-forever loop (network.rs:218-242), with seeded jitter on
        the backoff (the simulator's loop RNG when present, else a
        per-(dialer, peer) seed) so fleet-wide reconnect storms decorrelate."""
        rng = getattr(asyncio.get_event_loop(), "rng", None) or random.Random(
            (self.authority << 20) ^ peer
        )
        delay = 0.1
        while not self._stopped:
            try:
                host, port = self.addresses[peer]
                reader, writer = await asyncio.open_connection(host, port)
                writer.write(
                    HANDSHAKE_MAGIC.to_bytes(4, "little")
                    + self.authority.to_bytes(8, "little")
                )
                await writer.drain()
                ack = await asyncio.wait_for(_read_frame(reader), timeout=5.0)
                if (
                    int.from_bytes(ack[:4], "little") != HANDSHAKE_MAGIC
                    or int.from_bytes(ack[4:], "little") != peer
                ):
                    raise ConnectionError("bad handshake ack")
                delay = 0.1
                log.debug("dialed authority %d", peer)
                await self._run_peer(peer, reader, writer)
            except (OSError, asyncio.IncompleteReadError, ConnectionError, SerdeError,
                    asyncio.TimeoutError) as exc:
                log.debug("dial to authority %d failed: %r (retrying)", peer, exc)
            await asyncio.sleep(jittered_backoff(delay, rng))
            delay = min(delay * 2, 5.0)

    # -- shared read/write/ping loops --

    async def _run_peer(self, peer: int, reader, writer) -> None:
        conn = Connection(peer, latency_getter=lambda p=peer: self._latency.get(p, float("inf")))
        await self.connections.put(conn)

        async def read_loop():
            while True:
                frame = await _read_frame(reader)
                msg = decode_message(frame)
                if isinstance(msg, Ping):
                    await conn.sender.put(Pong(msg.nanos))
                    continue
                if isinstance(msg, Pong):
                    rtt = (time.monotonic_ns() - msg.nanos) / 1e9
                    prev = self._latency.get(peer)
                    self._latency[peer] = rtt if prev is None else 0.8 * prev + 0.2 * rtt
                    if self.metrics is not None:
                        self.metrics.connection_latency.labels(str(peer)).observe(rtt)
                    if rtt >= self.max_latency_s:
                        log.warning(
                            "latency breaker: authority %d RTT %.2fs >= %.2fs",
                            peer, rtt, self.max_latency_s,
                        )
                        raise ConnectionError("latency breaker tripped")
                    continue
                await conn.receiver.put(msg)

        async def write_loop():
            while True:
                msg = await conn.sender.get()
                _write_frame(writer, encode_message(msg))
                await writer.drain()

        async def ping_loop():
            while True:
                await conn.sender.put(Ping(time.monotonic_ns()))
                await asyncio.sleep(PING_INTERVAL_S)

        tasks = [
            asyncio.ensure_future(read_loop()),
            asyncio.ensure_future(write_loop()),
            asyncio.ensure_future(ping_loop()),
        ]
        try:
            done, pending = await asyncio.wait(tasks, return_when=asyncio.FIRST_COMPLETED)
        finally:
            for t in tasks:
                t.cancel()
            conn.close()
            writer.close()

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
