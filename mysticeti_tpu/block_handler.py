"""Pluggable application handlers driven by the core on block arrival/proposal.

Capability parity with ``mysticeti-core/src/block_handler.rs``:

* ``BlockHandler`` interface {handle_blocks, handle_proposal, state, recover_state,
  cleanup} (block_handler.rs:26-40)
* ``BenchmarkFastPathBlockHandler`` (:53-221) — pulls generated transactions from a
  queue (bounded by SOFT_MAX_PROPOSED_PER_BLOCK), registers own shares, tallies
  fast-path votes via TransactionAggregator, emits VoteRange replies, records
  certification latency metrics.
* ``TestBlockHandler`` (:224-333) — votes immediately and emits one fresh
  transaction per invocation; tracks proposed locators for test assertions.
* ``SimpleBlockHandler`` (:335-395) — production-style: shares raw tx bytes pushed
  by the application, acknowledging each via callback.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from .committee import Committee, QUORUM, TransactionAggregator
from .log import TransactionLog
from .runtime import now as runtime_now
from .serde import Reader, Writer
from .types import (
    AuthorityIndex,
    BaseStatement,
    BlockReference,
    Share,
    StatementBlock,
    TransactionLocator,
)

MAX_PROPOSED_PER_BLOCK = 10000


def _soft_max_from_env() -> int:
    raw = os.environ.get("MYSTICETI_MAX_BLOCK_TX")
    if raw is None:
        return MAX_PROPOSED_PER_BLOCK
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"MYSTICETI_MAX_BLOCK_TX must be an integer, got {raw!r}"
        ) from None
    if not 1 <= value <= MAX_PROPOSED_PER_BLOCK:
        raise ValueError(
            f"MYSTICETI_MAX_BLOCK_TX={value} out of range [1,"
            f" {MAX_PROPOSED_PER_BLOCK}] (the block_handler.rs SOFT_MAX regime"
            " caps proposals at the hard per-block maximum)"
        )
    return value


# Proposal drain cap (block_handler.rs SOFT_MAX equivalent).  Env-tunable:
# shrinking it raises the block rate at a given load, which reproduces the
# per-node block-arrival (and therefore signature-verification) rate of a
# large WAN committee on a small local fleet — the verification-bound regime
# of BASELINE configs #4/#5.
SOFT_MAX_PROPOSED_PER_BLOCK = _soft_max_from_env()


class BlockHandler:
    """Interface only; see module docstring."""

    def handle_blocks(
        self, blocks: Sequence[StatementBlock], require_response: bool
    ) -> List[BaseStatement]:
        raise NotImplementedError

    def handle_proposal(self, block: StatementBlock) -> None:
        raise NotImplementedError

    def state(self) -> bytes:
        raise NotImplementedError

    def recover_state(self, state: bytes, watermark_round=None) -> None:
        """``watermark_round`` bounds the Byzantine-oracle leniency after
        recovery (TransactionAggregator.with_state): pass the highest round
        durably replayed alongside the snapshot."""
        raise NotImplementedError

    def cleanup(self) -> None:
        pass

    def note_catchup(self, floor_round: int) -> None:
        """Snapshot catch-up (storage.py): blocks below ``floor_round`` are
        history this node will never process — the transaction oracles must
        treat votes/shares referencing it as expected, not Byzantine.
        Handlers carrying a TransactionAggregator forward to its
        ``relax_below``; stateless handlers ignore it."""
        votes = getattr(self, "transaction_votes", None)
        if votes is not None:
            votes.relax_below(floor_round)


class _LoggingAggregator(TransactionAggregator):
    """TransactionAggregator whose processed-hook appends to a TransactionLog
    (committee.rs:297-312 handler seam with the log.rs sink).

    Duplicate/unknown observations count on
    ``mysticeti_transaction_dedup_total{kind}`` — previously they were log
    lines (or a raise) only, so a fleet absorbing duplicate floods was
    indistinguishable from one that never saw them."""

    def __init__(
        self, log: Optional[TransactionLog], metrics=None
    ) -> None:
        super().__init__(QUORUM, track_processed=log is None)
        self._log = log
        self._metrics = metrics

    def _count_dedup(self, kind: str) -> None:
        if self._metrics is not None:
            self._metrics.mysticeti_transaction_dedup_total.labels(kind).inc()

    def transaction_processed(self, k: TransactionLocator) -> None:
        if self._log is not None:
            self._log.log(k)
        else:
            super().transaction_processed(k)

    def transaction_processed_range(self, block, start: int, end: int) -> None:
        if self._log is not None:
            self._log.log_range(block, start, end)
        else:
            super().transaction_processed_range(block, start, end)

    def duplicate_transaction(self, k, from_) -> None:
        self._count_dedup("duplicate")
        if self._log is None:
            super().duplicate_transaction(k, from_)

    def unknown_transaction(self, k, from_) -> None:
        self._count_dedup("unknown")
        if self._log is None:
            super().unknown_transaction(k, from_)


class BenchmarkFastPathBlockHandler(BlockHandler):
    """The benchmark fast path (block_handler.rs:53-221).

    Transactions arrive from the generator through ``submit``; ``handle_blocks``
    drains them (bounded) into Share statements and tallies votes; certification
    latency is recorded against ``transaction_time`` stamps made at proposal.
    """

    def __init__(
        self,
        committee: Committee,
        authority: AuthorityIndex,
        certified_log_path: Optional[str] = None,
        block_store=None,
        metrics=None,
        transaction_time: Optional[Dict[BlockReference, float]] = None,
        ingress=None,
    ) -> None:
        log = TransactionLog.start(certified_log_path) if certified_log_path else None
        self.transaction_votes = _LoggingAggregator(log, metrics=metrics)
        # Keyed per OWN proposal block: all shares of a block are drained
        # at one moment, so one stamp covers the whole run.
        self.transaction_time: Dict[BlockReference, float] = (
            transaction_time if transaction_time is not None else {}
        )
        self._time_lock = threading.Lock()
        self.committee = committee
        self.authority = authority
        self.block_store = block_store
        self.metrics = metrics
        self._queue: Deque[List[bytes]] = deque()
        self._queue_lock = threading.Lock()
        # Legacy-path deferral accounting: length of the already-counted
        # deferred remainder sitting at the FRONT of the queue (appendleft
        # puts it there), so a batch re-truncated across several proposals
        # counts each transaction's deferral once, not once per proposal.
        self._deferred_counted = 0
        self.pending_transactions = 0
        self.consensus_only = "CONSENSUS_ONLY" in os.environ
        # Ingress plane (ingress.IngressPlane): when wired, submissions run
        # through the admission-controlled mempool (dedup, fairness lanes,
        # typed shedding) and proposals drain weighted-round-robin from it.
        # None = the legacy unbounded direct queue.
        self.ingress = ingress

    # -- ingestion from the generator / gateway --

    def submit(self, transactions: List[bytes]):
        """Submit transactions for proposal.  With an ingress plane wired,
        returns its typed :class:`~mysticeti_tpu.ingress.SubmitResult`
        (ACK/QUEUED/SHED) — closed-loop clients consume it; legacy callers
        may ignore the return value (the pre-ingress contract returned
        None)."""
        if self.ingress is not None:
            return self.ingress.submit("local", transactions)
        with self._queue_lock:
            self._queue.append(transactions)
        return None

    def _proposal_budget(self) -> int:
        cap = SOFT_MAX_PROPOSED_PER_BLOCK
        if self.ingress is not None and self.ingress.max_per_proposal:
            cap = min(
                max(1, self.ingress.max_per_proposal), MAX_PROPOSED_PER_BLOCK
            )
        return cap - self.pending_transactions

    def _receive_with_limit(self) -> Optional[List[bytes]]:
        """Drain up to the SOFT_MAX budget, SLICING oversize submissions: the
        generator submits 100 ms chunks (tps/10 transactions each), and
        admitting a whole chunk because the budget had one slot left would
        let every block overshoot the cap by the chunk size — turning the
        block_handler.rs SOFT_MAX semantics (a per-block transaction cap)
        into a no-op whenever tps/10 > SOFT_MAX.  The unconsumed remainder
        stays queued for the next proposal — visible on
        ``mysticeti_ingress_shed_total{soft_cap_deferred}`` (deferred, not
        lost; previously this truncation was silent)."""
        budget = self._proposal_budget()
        if budget <= 0:
            return None
        if self.ingress is not None:
            received = self.ingress.drain(budget)
            if not received:
                return None
            self.pending_transactions += len(received)
            return received
        with self._queue_lock:
            if not self._queue:
                return None
            received = self._queue.popleft()
            already_counted = self._deferred_counted
            self._deferred_counted = 0
            if len(received) > budget:
                remainder = len(received) - budget
                self._queue.appendleft(received[budget:])
                # Only transactions ENTERING deferral count: the front batch
                # may itself be a previously-deferred (and counted)
                # remainder, and re-counting it every proposal would inflate
                # the series past the number of offered transactions.
                newly = remainder - max(0, already_counted - budget)
                self._deferred_counted = remainder
                if newly > 0 and self.metrics is not None:
                    self.metrics.mysticeti_ingress_shed_total.labels(
                        "soft_cap_deferred"
                    ).inc(newly)
                received = received[:budget]
        self.pending_transactions += len(received)
        return received

    # -- BlockHandler --

    def handle_blocks(self, blocks, require_response):
        response: List[BaseStatement] = []
        if require_response:
            while (received := self._receive_with_limit()) is not None:
                response.extend(Share(tx) for tx in received)
        # transaction_time stamps are local to this process (own proposals),
        # so certify latency is an interval on the runtime clock — monotonic
        # in production (an NTP step must not dent the latency channels) and
        # virtual under the DeterministicLoop simulator.
        now = runtime_now()
        for block in blocks:
            if self.consensus_only:
                continue
            processed = self.transaction_votes.process_block(
                block, response if require_response else None, self.committee
            )
            if self.metrics is not None and processed:
                # Certification arrives as ranges; every offset of a run was
                # proposed together so they share ONE submission timestamp
                # (transaction_time is keyed per own block).
                import numpy as np

                lat_values, lat_counts = [], []
                with self._time_lock:
                    for rng in processed:
                        created = self.transaction_time.get(rng.block)
                        if created is None:
                            continue
                        lat_values.append(max(0.0, now - created))
                        lat_counts.append(
                            rng.offset_end_exclusive
                            - rng.offset_start_inclusive
                        )
                if lat_values:
                    self.metrics.observe_latency_batch(
                        "owned", np.repeat(lat_values, lat_counts)
                    )
                    # Exact-percentile channel (metrics.rs:60): one sample
                    # per certified RANGE (all offsets of a run share one
                    # submission stamp) — bounds the cost at load; the
                    # per-tx-weighted distribution lives in latency_s{owned}.
                    certified = self.metrics.transaction_certified_latency
                    for v in lat_values:
                        certified.observe(v)
        if self.metrics is not None:
            self.metrics.block_handler_pending_certificates.set(
                len(self.transaction_votes)
            )
        return response

    def handle_proposal(self, block: StatementBlock) -> None:
        n_shared = sum(
            1 for st in block.statements if isinstance(st, Share)
        )
        self.pending_transactions -= n_shared
        if n_shared:
            # One stamp per OWN proposal: every share of the block was
            # drained at the same moment, so per-transaction stamps (a dict
            # entry per tx) carried no information — only cost.  Runtime
            # clock: every reader measures an interval in this same process.
            with self._time_lock:
                self.transaction_time[block.reference] = runtime_now()
        if not self.consensus_only:
            from .committee import shared_ranges

            for rng in shared_ranges(block):
                self.transaction_votes.register(rng, self.authority, self.committee)

    def state(self) -> bytes:
        return self.transaction_votes.state()

    def recover_state(self, state: bytes, watermark_round=None) -> None:
        self.transaction_votes.with_state(state, watermark_round)

    # Stamps are per OWN PROPOSAL BLOCK (not per tx), so residency is cheap
    # (~blocks/s * window entries).  The window must comfortably exceed the
    # worst-case certify/commit latency the metrics can express (buckets run
    # to 90 s): a shorter window silently censors exactly the slow samples
    # the latency channels exist to expose — degraded runs would read
    # healthy.
    TRANSACTION_TIME_RETENTION_S = 120.0

    def cleanup(self) -> None:
        cutoff = runtime_now() - self.TRANSACTION_TIME_RETENTION_S
        with self._time_lock:
            # Mutate IN PLACE: the commit observer shares this dict
            # (validator.py wires handler.transaction_time into
            # TestCommitObserver) — rebinding would freeze the observer on
            # the pre-cleanup object and silence its latency channels.
            stale = [
                k for k, v in self.transaction_time.items() if v < cutoff
            ]
            for k in stale:
                del self.transaction_time[k]


class TestBlockHandler(BlockHandler):
    """Immediately votes and generates one new transaction per call
    (block_handler.rs:224-333)."""

    __test__ = False  # not a pytest class

    def __init__(
        self,
        last_transaction: int,
        committee: Committee,
        authority: AuthorityIndex,
        metrics=None,
    ) -> None:
        self.last_transaction = last_transaction
        self.transaction_votes = TransactionAggregator(QUORUM)
        self.committee = committee
        self.authority = authority
        self.proposed: List[TransactionLocator] = []
        self.metrics = metrics
        # Out-of-band payloads (e.g. reconfig committee-change transactions,
        # reconfig.py) planted by a harness; drained ahead of the generated
        # counter transaction on the next proposal.
        self.pending_inject: Deque[bytes] = deque()

    def inject(self, payload: bytes) -> None:
        """Queue an arbitrary transaction payload for the next own proposal."""
        self.pending_inject.append(payload)

    def is_certified(self, locator: TransactionLocator) -> bool:
        return self.transaction_votes.is_processed(locator)

    @staticmethod
    def make_transaction(i: int) -> bytes:
        return i.to_bytes(8, "little")

    def handle_blocks(self, blocks, require_response):
        response: List[BaseStatement] = []
        if require_response:
            for block in blocks:
                if block.author() == self.authority:
                    # Own blocks can resurface during recovery; keep the
                    # transaction counter monotone (block_handler.rs:268-281).
                    for st in block.statements:
                        if isinstance(st, Share):
                            self.last_transaction += 1
            while self.pending_inject:
                response.append(Share(self.pending_inject.popleft()))
            self.last_transaction += 1
            response.append(Share(self.make_transaction(self.last_transaction)))
        for block in blocks:
            self.transaction_votes.process_block(
                block, response if require_response else None, self.committee
            )
        return response

    def handle_proposal(self, block: StatementBlock) -> None:
        from .committee import shared_ranges

        for locator, _ in block.shared_transactions():
            self.proposed.append(locator)
        for rng in shared_ranges(block):
            self.transaction_votes.register(rng, self.authority, self.committee)

    def state(self) -> bytes:
        w = Writer()
        w.bytes(self.transaction_votes.state())
        w.u64(self.last_transaction)
        return w.finish()

    def recover_state(self, state: bytes, watermark_round=None) -> None:
        r = Reader(state)
        self.transaction_votes.with_state(r.bytes(), watermark_round)
        self.last_transaction = r.u64()
        r.expect_done()


class SimpleBlockHandler(BlockHandler):
    """Production-style: share raw transaction bytes pushed by the application;
    acknowledge each once drained into a proposal (block_handler.rs:335-395)."""

    def __init__(self) -> None:
        self._queue: Deque[Tuple[bytes, Optional[Callable[[], None]]]] = deque()
        self._lock = threading.Lock()

    def submit(self, tx_bytes: bytes, done: Optional[Callable[[], None]] = None) -> None:
        with self._lock:
            self._queue.append((tx_bytes, done))

    def handle_blocks(self, blocks, require_response):
        if not require_response:
            return []
        response: List[BaseStatement] = []
        while len(response) < MAX_PROPOSED_PER_BLOCK:
            with self._lock:
                if not self._queue:
                    break
                tx_bytes, done = self._queue.popleft()
            response.append(Share(tx_bytes))
            if done is not None:
                done()
        return response

    def handle_proposal(self, block: StatementBlock) -> None:
        pass

    def state(self) -> bytes:
        return b""

    def recover_state(self, state: bytes, watermark_round=None) -> None:
        pass
