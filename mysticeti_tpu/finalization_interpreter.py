"""Offline whole-DAG re-interpretation: the fast-path safety oracle.

Capability parity with ``mysticeti-core/src/finalization_interpreter.rs``
(:13-148): recompute, from the stored DAG alone, which transactions are
finalized (certified by a quorum of certifying blocks) and which blocks certify
them.  Used by the simulation safety test to cross-check the online
TransactionAggregator/commit pipeline against an independent implementation.

Semantics: a block votes for a transaction if it shares it, votes for it
explicitly, or (transitively) includes a block that voted; a block whose
accumulated voter stake reaches quorum *certifies* the transaction (unless the
block carries the epoch-change marker); a transaction is *finalized* once
certifying blocks from a quorum of distinct authors exist.
"""
from __future__ import annotations

from typing import Dict, List, Set, Tuple

from .block_store import BlockStore
from .committee import Committee, QUORUM, StakeAggregator
from .types import (
    BlockReference,
    Share,
    StatementBlock,
    TransactionLocator,
    Vote,
    VoteRange,
)


class FinalizationInterpreter:
    def __init__(self, block_store: BlockStore, committee: Committee) -> None:
        self.block_store = block_store
        self.committee = committee
        # per-block: tx -> voter-stake aggregator
        self.transaction_aggregator: Dict[
            BlockReference, Dict[TransactionLocator, StakeAggregator]
        ] = {}
        self.certificate_aggregator: Dict[TransactionLocator, StakeAggregator] = {}
        self.transaction_certificates: Dict[
            TransactionLocator, Set[BlockReference]
        ] = {}
        self.finalized_transactions: Set[TransactionLocator] = set()

    def finalized_tx_certifying_blocks(
        self,
    ) -> List[Tuple[TransactionLocator, Set[BlockReference]]]:
        for round_ in range(self.block_store.highest_round() + 1):
            for block in self.block_store.get_blocks_by_round(round_):
                self._process(block)
        return [
            (tx, blocks)
            for tx, blocks in self.transaction_certificates.items()
            if tx in self.finalized_transactions
        ]

    def _process(self, block: StatementBlock) -> None:
        if block.reference in self.transaction_aggregator:
            return
        self.transaction_aggregator[block.reference] = {}

        for offset, statement in enumerate(block.statements):
            if isinstance(statement, Vote):
                if statement.accept:
                    self._vote(block, statement.locator, block.author())
            elif isinstance(statement, VoteRange):
                for locator in statement.range.locators():
                    self._vote(block, locator, block.author())
            elif isinstance(statement, Share):
                self._vote(
                    block,
                    TransactionLocator(block.reference, offset),
                    block.author(),
                )

        for parent_ref in block.includes:
            parent = self.block_store.get_block(parent_ref)
            assert parent is not None, "whole DAG must be stored"
            self._process(parent)
            # Inherit every vote visible through the parent.
            parent_aggregator = self.transaction_aggregator[parent_ref]
            self.transaction_aggregator[parent_ref] = {}
            for tx, agg in parent_aggregator.items():
                for voter in agg.voters():
                    self._vote(block, tx, voter)
            self.transaction_aggregator[parent_ref] = parent_aggregator

    def _vote(
        self,
        block: StatementBlock,
        transaction: TransactionLocator,
        tx_voter: int,
    ) -> None:
        aggs = self.transaction_aggregator[block.reference]
        agg = aggs.get(transaction)
        if agg is None:
            agg = aggs[transaction] = StakeAggregator(QUORUM)
        if agg.add(tx_voter, self.committee) and not block.epoch_changed():
            # ``block`` certifies this transaction.
            self.transaction_certificates.setdefault(transaction, set()).add(
                block.reference
            )
            cert = self.certificate_aggregator.get(transaction)
            if cert is None:
                cert = self.certificate_aggregator[transaction] = StakeAggregator(QUORUM)
            if cert.add(block.author(), self.committee):
                self.finalized_transactions.add(transaction)
