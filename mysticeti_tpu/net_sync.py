"""Node-level orchestration: connections, verify-then-add pipeline, timeouts.

Capability parity with ``mysticeti-core/src/net_sync.rs``:

* ``NetworkSyncer.start`` (:80-167) — Syncer + Signals, core dispatcher,
  connection accept loop, leader-timeout task, periodic cleanup task, WAL
  fsync thread.
* per-peer ``connection_task`` (:237-312) — subscribe to the peer's own blocks
  from our last-seen round, dispatch incoming messages.
* ``process_blocks`` (:314-386) — dedup via the core task, consensus-rule
  verification, then the pluggable ``BlockVerifier`` — here the
  **batched TPU signature path** (the reference verifies serially per
  connection; this framework batches across connections, block_validator.py).
* leader timeout (:401-444), cleanup every 10 s (:446-459), epoch-aware
  shutdown (:466-494), ``AsyncWalSyncer`` 1 s fsync cadence (:496-560).
"""
from __future__ import annotations

import asyncio
import threading
from typing import Dict, List, Optional, Set

from .block_validator import AcceptAllBlockVerifier, BlockVerifier
from .commit_observer import CommitObserver
from .config import Parameters
from .core import Core
from .core_task import CoreTaskDispatcher
from .network import (
    BlockNotFound,
    Blocks,
    Connection,
    RequestBlocks,
    RequestBlocksResponse,
    SubscribeOwnFrom,
)
from .syncer import Syncer, SyncerSignals
from .synchronizer import BlockDisseminator, BlockFetcher
from .types import AuthoritySet, StatementBlock, VerificationError

CLEANUP_INTERVAL_S = 10.0


class AsyncSignals(SyncerSignals):
    """Signals backed by asyncio primitives (syncer.rs:24-52)."""

    def __init__(self) -> None:
        self.block_ready = asyncio.Event()
        self.round_advanced = asyncio.Condition()
        self.current_round = 0

    def new_block_ready(self) -> None:
        self.block_ready.set()
        # Re-arm on the next loop tick so stream tasks level-trigger.
        asyncio.get_event_loop().call_soon(self.block_ready.clear)

    def new_round(self, round_: int) -> None:
        self.current_round = round_

        async def notify():
            async with self.round_advanced:
                self.round_advanced.notify_all()

        asyncio.ensure_future(notify())


class NetworkSyncer:
    def __init__(
        self,
        core: Core,
        commit_observer: CommitObserver,
        network,  # TcpNetwork-like: .connections queue
        parameters: Optional[Parameters] = None,
        block_verifier: Optional[BlockVerifier] = None,
        metrics=None,
        start_wal_sync_thread: bool = False,
    ) -> None:
        self.parameters = parameters or Parameters()
        self.signals = AsyncSignals()
        self.syncer = Syncer(
            core,
            self.parameters.wave_length,
            self.signals,
            commit_observer,
            metrics,
        )
        self.core = core
        self.network = network
        self.block_verifier = block_verifier or AcceptAllBlockVerifier()
        self.metrics = metrics
        self.dispatcher = CoreTaskDispatcher(self.syncer)
        self.connections: Dict[int, Connection] = {}
        self.connected_authorities = AuthoritySet()
        self.fetcher = BlockFetcher(
            core.authority,
            self.dispatcher,
            self.connections,
            self.parameters.synchronizer,
            metrics,
        )
        self._tasks: List[asyncio.Task] = []
        self._disseminators: Dict[int, BlockDisseminator] = {}
        self._stopped = asyncio.Event()
        self._wal_sync_thread: Optional[threading.Thread] = None
        self._start_wal_sync_thread = start_wal_sync_thread

    # -- lifecycle --

    async def start(self) -> "NetworkSyncer":
        self.dispatcher.start()
        self.connected_authorities.insert(self.core.authority)
        # Initial proposal attempt (validator genesis kick, net_sync.rs:97).
        await self.dispatcher.force_new_block(1, self.connected_authorities.copy())
        self._tasks.append(asyncio.ensure_future(self._accept_loop()))
        self._tasks.append(asyncio.ensure_future(self._leader_timeout_task()))
        self._tasks.append(asyncio.ensure_future(self._cleanup_task()))
        self.fetcher.start()
        if self._start_wal_sync_thread:
            self._start_wal_syncer()
        return self

    def _start_wal_syncer(self) -> None:
        """Dedicated fsync thread, 1 s cadence (net_sync.rs:496-560)."""
        syncer = self.core.wal_syncer()
        stop = self._stopped

        def run():
            import time as _time

            while not stop.is_set():
                _time.sleep(1.0)
                try:
                    syncer.sync()
                except OSError:
                    return

        self._wal_sync_thread = threading.Thread(
            target=run, name="wal-syncer", daemon=True
        )
        self._wal_sync_thread.start()

    async def stop(self) -> None:
        self._stopped.set()
        self.fetcher.stop()
        for d in self._disseminators.values():
            d.stop()
        for t in self._tasks:
            t.cancel()
        self.dispatcher.stop()
        for c in self.connections.values():
            c.close()
        if hasattr(self.network, "stop"):
            await self.network.stop()

    async def await_completion(self) -> None:
        await self._stopped.wait()

    # -- connection handling --

    async def _accept_loop(self) -> None:
        while True:
            connection: Connection = await self.network.connections.get()
            self._tasks.append(
                asyncio.ensure_future(self._connection_task(connection))
            )

    async def _connection_task(self, connection: Connection) -> None:
        """net_sync.rs:237-312."""
        peer = connection.peer
        self.connections[peer] = connection
        self.connected_authorities.insert(peer)
        disseminator = BlockDisseminator(
            connection,
            self.core.block_store,
            self.signals.block_ready,
            self.parameters.synchronizer,
            self.metrics,
        )
        self._disseminators[peer] = disseminator
        # Ask the peer for its own blocks we have not yet seen.
        last_seen = self.core.block_store.last_seen_by_authority(peer)
        await connection.send(SubscribeOwnFrom(last_seen))
        try:
            while True:
                msg = await connection.recv()
                if msg is None:
                    break
                if isinstance(msg, SubscribeOwnFrom):
                    disseminator.subscribe_own_from(msg.round)
                elif isinstance(msg, Blocks):
                    await self._process_blocks(msg.blocks)
                elif isinstance(msg, RequestBlocks):
                    await disseminator.send_requested(list(msg.references))
                elif isinstance(msg, RequestBlocksResponse):
                    await self._process_blocks(msg.blocks)
                elif isinstance(msg, BlockNotFound):
                    if self.metrics is not None:
                        self.metrics.block_sync_requests_failed.inc(
                            len(msg.references)
                        )
        finally:
            disseminator.stop()
            self._disseminators.pop(peer, None)
            if self.connections.get(peer) is connection:
                del self.connections[peer]
            connection.close()

    # -- the receive pipeline (net_sync.rs:314-386) --

    async def _process_blocks(self, serialized_blocks) -> None:
        blocks: List[StatementBlock] = []
        for raw in serialized_blocks:
            try:
                block = StatementBlock.from_bytes(raw)
            except Exception:
                continue  # malformed: drop (byzantine peer)
            blocks.append(block)
        if not blocks:
            return
        # Dedup through the core task before paying for verification.
        processed = await self.dispatcher.processed([b.reference for b in blocks])
        fresh = [b for b, done in zip(blocks, processed) if not done]
        verified: List[StatementBlock] = []
        for block in fresh:
            try:
                block.verify_structure(self.core.committee)
            except VerificationError:
                continue
            verified.append(block)
        if not verified:
            return
        # Signature + application check through the pluggable verifier
        # (batched across connections on TPU).
        results = await self.block_verifier.verify_blocks(verified)
        accepted = [b for b, ok in zip(verified, results) if ok]
        if not accepted:
            return
        missing = await self.dispatcher.add_blocks(
            accepted, self.connected_authorities.copy()
        )
        if missing:
            # Request missing causal history from whoever sent us the children.
            for peer, conn in list(self.connections.items()):
                conn.try_send(RequestBlocks(tuple(missing[:50])))
                break

    # -- background tasks --

    async def _leader_timeout_task(self) -> None:
        """net_sync.rs:401-444: force a proposal if the round stalls."""
        timeout = self.parameters.leader_timeout_s
        while True:
            round_at_start = self.signals.current_round
            try:
                async with self.signals.round_advanced:
                    await asyncio.wait_for(
                        self.signals.round_advanced.wait(), timeout=timeout
                    )
            except asyncio.TimeoutError:
                if self.core.epoch_closed():
                    continue
                await self.dispatcher.force_new_block(
                    round_at_start + 1, self.connected_authorities.copy()
                )

    async def _cleanup_task(self) -> None:
        while True:
            await asyncio.sleep(CLEANUP_INTERVAL_S)
            if self.parameters.enable_cleanup:
                await self.dispatcher.cleanup()
