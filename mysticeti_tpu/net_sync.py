"""Node-level orchestration: connections, verify-then-add pipeline, timeouts.

Capability parity with ``mysticeti-core/src/net_sync.rs``:

* ``NetworkSyncer.start`` (:80-167) — Syncer + Signals, core dispatcher,
  connection accept loop, leader-timeout task, periodic cleanup task, WAL
  fsync thread.
* per-peer ``connection_task`` (:237-312) — subscribe to the peer's own blocks
  from our last-seen round, dispatch incoming messages.
* ``process_blocks`` (:314-386) — dedup via the core task, consensus-rule
  verification, then the pluggable ``BlockVerifier`` — here the
  **batched TPU signature path** (the reference verifies serially per
  connection; this framework batches across connections, block_validator.py).
* leader timeout (:401-444), cleanup every 10 s (:446-459), epoch-aware
  shutdown (:466-494), ``AsyncWalSyncer`` 1 s fsync cadence (:496-560).
"""
from __future__ import annotations

import asyncio
import contextlib
import threading
from typing import Dict, List, Optional, Set

from . import spans
from .block_validator import AcceptAllBlockVerifier, BlockVerifier
from .commit_observer import CommitObserver
from .config import Parameters, ROUNDS_IN_EPOCH_MAX
from .core import Core
from .core_task import CoreTaskDispatcher, DataPlaneOffload
from .network import (
    BlockNotFound,
    Blocks,
    Connection,
    EpochInfo,
    RequestBlocks,
    RequestBlocksResponse,
    RequestSnapshot,
    RequestSnapshotStream,
    SnapshotResponse,
    SubscribeOthersFrom,
    SubscribeOwnFrom,
    TimestampedBlocks,
    wall_jump_us,
)
from .syncer import Syncer, SyncerSignals
from .tracing import logger
from .utils.tasks import spawn_logged

log = logger(__name__)
from .network import mesh_legacy
from .synchronizer import (
    BlockDisseminator,
    BlockFetcher,
    FrameCache,
    HelperSubscriptions,
)
from .types import AuthoritySet, StatementBlock, VerificationError

CLEANUP_INTERVAL_S = 10.0

# Sender stamp pairs whose wall/monotonic deltas disagree by more than this
# mean the peer's wall clock stepped between frames (see network.wall_jump_us)
# — generous against NTP slew over the 1 s stream cadence, tight against
# actual steps.
WALL_JUMP_TOLERANCE_US = 50_000


class Notify:
    """Lost-wakeup-free notification (the tokio ``Notify::notified`` shape).

    ``subscribe()`` hands out the CURRENT event object; ``notify()`` sets it
    and installs a fresh one.  A consumer that subscribes BEFORE checking its
    condition can never miss a notification that follows the check — unlike
    the set-then-``call_soon``-clear Event pattern, where a task awaiting
    between set and clear lost the edge.

    ``generation`` counts notifications: the dissemination FrameCache keys
    entries on it, so a frame built before a new block landed can never be
    served after (the key simply stops matching) — cheap whole-cache
    invalidation without a registry of entries.
    """

    __slots__ = ("_event", "generation")

    def __init__(self) -> None:
        self._event = asyncio.Event()
        self.generation = 0

    def subscribe(self) -> asyncio.Event:
        return self._event

    def notify(self) -> None:
        self.generation += 1
        event, self._event = self._event, asyncio.Event()
        event.set()


class AsyncSignals(SyncerSignals):
    """Signals backed by asyncio primitives (syncer.rs:24-52)."""

    def __init__(self) -> None:
        self.block_ready = Notify()
        self.round_notify = Notify()
        self.current_round = 0

    def new_block_ready(self) -> None:
        self.block_ready.notify()

    def new_round(self, round_: int) -> None:
        self.current_round = round_
        self.round_notify.notify()


class NetworkSyncer:
    def __init__(
        self,
        core: Core,
        commit_observer: CommitObserver,
        network,  # TcpNetwork-like: .connections queue
        parameters: Optional[Parameters] = None,
        block_verifier: Optional[BlockVerifier] = None,
        metrics=None,
        start_wal_sync_thread: bool = False,
        recorder=None,
    ) -> None:
        self.parameters = parameters or Parameters()
        self.signals = AsyncSignals()
        self.syncer = Syncer(
            core,
            self.parameters.wave_length,
            self.signals,
            commit_observer,
            metrics,
        )
        self.core = core
        self.network = network
        self.block_verifier = block_verifier or AcceptAllBlockVerifier()
        self.metrics = metrics
        self.dispatcher = CoreTaskDispatcher(self.syncer, metrics=metrics)
        # Batched native decode+digest off the event loop (core_task.py):
        # inert (inline path) under sims, without the extension, or for
        # small frames — see DataPlaneOffload.should_offload.
        self.dataplane_offload = DataPlaneOffload(metrics=metrics)
        # Bound once: _decode_fresh is per-incoming-frame hot.
        self._utilization_timer = (
            metrics.utilization_timer
            if metrics is not None
            else (lambda _name: contextlib.nullcontext())
        )
        self.connections: Dict[int, Connection] = {}
        self.connected_authorities = AuthoritySet()
        self.fetcher = BlockFetcher(
            core.authority,
            self.dispatcher,
            self.connections,
            self.parameters.synchronizer,
            metrics,
        )
        self._tasks: List[asyncio.Task] = []
        self._disseminators: Dict[int, BlockDisseminator] = {}
        # Encode-once fan-out (synchronizer.FrameCache): one shared cache
        # across every peer's disseminator, so N-1 subscribers at the same
        # cursor ship one serialization.  MYSTICETI_MESH_LEGACY=1 restores
        # the per-peer build path (the A/B baseline).
        self.frame_cache = None if mesh_legacy() else FrameCache(metrics)
        # Helper-stream bookkeeping (requester side; armed by the
        # disseminate_others_blocks knob): which connected peers relay which
        # unreachable authority's blocks for us, within the config caps.
        self._helper_subs = HelperSubscriptions(self.parameters.synchronizer)
        # Content-silence scoring (docs/adversary.md): consecutive missing-
        # parent fetches per author with no intervening DIRECT delivery of
        # that author's own blocks.  A live connection that never delivers
        # its own proposals (a withholder, or a grey-failed sender) looks
        # exactly like this; past the threshold we arm relay streams for it
        # as if its connection had dropped — the fetch path stops taxing
        # the quorum path one round-trip per round.
        self._fetch_gap_by_author: Dict[int, int] = {}
        self._stopped = asyncio.Event()
        self._wal_sync_thread: Optional[threading.Thread] = None
        self._start_wal_sync_thread = start_wal_sync_thread
        # Snapshot catch-up serving totals, surviving connection teardown
        # (the per-connection disseminator dies with its peer): the artifact
        # and tests read how much bootstrap data this node shipped.
        self.snapshot_blocks_served = 0
        self.snapshot_bytes_served = 0
        # Flight recorder (flight_recorder.py): connection churn, leader
        # timeouts, and sync decisions are exactly the "seconds before the
        # incident" events its ring exists for.  None = not recording.
        self.recorder = recorder
        # Epoch reconfiguration (reconfig.py): last epoch each peer reported
        # over the tag-17 extension, plus the listener that re-derives the
        # relay/peer bookkeeping and re-broadcasts EpochInfo on a switch.
        self.peer_epochs: Dict[int, int] = {}
        if getattr(core, "reconfig", None) is not None:
            core.epoch_listeners.append(self._on_epoch_switch)

    def _record(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record(kind, **fields)

    # -- lifecycle --

    async def start(self) -> "NetworkSyncer":
        self.dispatcher.start()
        self.connected_authorities.insert(self.core.authority)
        # Initial proposal attempt (validator genesis kick, net_sync.rs:97).
        await self.dispatcher.force_new_block(
            1, self.connected_authorities.copy(), genesis=True
        )
        self._tasks.append(spawn_logged(self._accept_loop(), log))
        self._tasks.append(spawn_logged(self._leader_timeout_task(), log))
        self._tasks.append(spawn_logged(self._cleanup_task(), log))
        if self.parameters.rounds_in_epoch < ROUNDS_IN_EPOCH_MAX:
            self._tasks.append(spawn_logged(self._epoch_watch_task(), log))
        self.fetcher.start()
        if self._start_wal_sync_thread:
            self._start_wal_syncer()
        return self

    def _start_wal_syncer(self) -> None:
        """Dedicated fsync thread, 1 s cadence (net_sync.rs:496-560)."""
        syncer = self.core.wal_syncer()
        stop = self._stopped
        size_gauge = self.metrics.wal_size_bytes if self.metrics else None
        segments_gauge = self.metrics.wal_segments if self.metrics else None
        wal_writer = self.core.wal_writer

        def run():
            import time as _time

            while not stop.is_set():
                _time.sleep(1.0)
                try:
                    syncer.sync()
                except OSError:
                    return
                if size_gauge is not None:
                    # Live bytes across every surviving segment — the old
                    # single-file read (the append position) over-reports
                    # by exactly the GC-reclaimed bytes once segments roll;
                    # sampled here so the gauge costs one set per second.
                    size_gauge.set(wal_writer.size_bytes())
                if segments_gauge is not None:
                    segments_gauge.set(wal_writer.segment_count())

        self._wal_sync_thread = threading.Thread(
            target=run, name="wal-syncer", daemon=True
        )
        self._wal_sync_thread.start()

    async def stop(self) -> None:
        self._stopped.set()
        self.fetcher.stop()
        for d in self._disseminators.values():
            d.stop()
        for t in self._tasks:
            if t is not asyncio.current_task():
                t.cancel()
        self.dispatcher.stop()
        self.dataplane_offload.stop()
        for c in self.connections.values():
            c.close()
        if hasattr(self.network, "stop"):
            await self.network.stop()

    async def await_completion(self) -> None:
        await self._stopped.wait()

    def backpressure(self) -> Dict[str, object]:
        """Live core backpressure signals for the ingress plane's admission
        controller (ingress.py): the consensus owner's queue depth and the
        WAL appender's drain state — cheap reads of state the node already
        maintains, no new bookkeeping."""
        return {
            "core_queue_depth": self.dispatcher.queue_depth(),
            "core_queue_capacity": self.dispatcher.queue_capacity,
            "wal_backlog": bool(self.core.wal_writer.pending()),
        }

    # -- connection handling --

    async def _accept_loop(self) -> None:
        while True:
            connection: Connection = await self.network.connections.get()
            self._tasks.append(
                spawn_logged(self._connection_task(connection), log)
            )

    # Max verification groups in flight per connection: deep enough that a
    # remote accelerator's per-dispatch round-trip (~100-300 ms tunneled)
    # overlaps many batches, small enough to backpressure a flooding peer.
    VERIFY_PIPELINE_DEPTH = 32

    async def _connection_task(self, connection: Connection) -> None:
        """net_sync.rs:237-312."""
        peer = connection.peer
        log.debug("connection established with authority %d", peer)
        self._record("peer-connect", peer=peer)
        self.connections[peer] = connection
        self.connected_authorities.insert(peer)
        disseminator = BlockDisseminator(
            connection,
            self.core.block_store,
            self.signals.block_ready,
            self.parameters.synchronizer,
            self.metrics,
            frame_cache=self.frame_cache,
        )
        self._disseminators[peer] = disseminator
        # Ask the peer for its own blocks we have not yet seen.
        last_seen = self.core.block_store.last_seen_by_authority(peer)
        await connection.send(SubscribeOwnFrom(last_seen))
        # A direct stream from this authority makes any relay of its blocks
        # redundant; forgetting the ask lets a later outage re-request.
        self._helper_subs.drop_authority(peer)
        if self.parameters.reconfig and self.core.reconfig is not None:
            # Tag-17 soft extension: advertise our epoch + committee digest
            # right after the fixed hello (version-skew safe — only sent
            # when the knob is on, and advisory on the receiving side).
            await connection.send(
                EpochInfo(self.core.committee.epoch, self.core.reconfig.digest())
            )
        if self.parameters.synchronizer.disseminate_others_blocks:
            await self._request_helper_streams(connection)
        if self.parameters.storage.snapshot_catchup:
            # Snapshot catch-up ask (storage.py): tell the peer our commit
            # height; a peer far enough ahead answers with its manifest +
            # the retained block window, anyone else ignores it.  Cheap (one
            # small frame per connect) and self-gating on both sides.
            await connection.send(RequestSnapshot(self.core.commit_height()))
        # Per-connection verification pipeline: the reader overlaps many
        # in-flight signature batches (the accelerator's round-trip would
        # otherwise serialize the connection at one batch per RTT), while the
        # accept loop awaits results IN ORDER so blocks enter the core in the
        # stream order the peer sent them (no spurious missing-parent
        # requests).
        pipeline: asyncio.Queue = asyncio.Queue(maxsize=self.VERIFY_PIPELINE_DEPTH)
        # Same-connection dedup window: dispatcher.processed only knows blocks
        # that finished the pipeline, so without this a peer retransmitting a
        # block back-to-back would get every copy signature-verified while the
        # first is still in flight.
        inflight: Set[bytes] = set()
        # Last sender stamp pair per tag-12 frame (wall-jump detection).
        last_stamp: Optional[tuple] = None
        # One-shot arming for the snapshot bulk stream: serving a manifest
        # to this peer arms exactly one RequestSnapshotStream (re-arming
        # requires another gap-checked RequestSnapshot), so a caught-up or
        # misbehaving peer cannot turn the one-u64 ask into a repeated
        # full-window push.
        snapshot_armed_floor: Optional[int] = None
        accept_task = asyncio.ensure_future(
            self._accept_ordered(pipeline, connection, inflight)
        )
        try:
            while True:
                msg = await connection.recv()
                if msg is None:
                    break
                if isinstance(msg, SubscribeOwnFrom):
                    disseminator.subscribe_own_from(msg.round)
                elif isinstance(msg, SubscribeOthersFrom):
                    # Serving side of the helper streams: answer whenever
                    # asked (the knob governs ASKING; the disseminator's
                    # absolute cap bounds what one peer can demand).
                    disseminator.subscribe_others_from(
                        msg.authority, msg.round
                    )
                elif isinstance(msg, (Blocks, RequestBlocksResponse)):
                    transit = None
                    if (
                        isinstance(msg, TimestampedBlocks)
                        and msg.sent_wall_ns
                    ):
                        # Wire-timestamp extension (tag 12): raw transit is
                        # SIGNED (clock skew can drive it negative) — the
                        # histogram clamps, the trace keeps the raw value
                        # for the fleet merger's skew estimator.  The
                        # monotonic stamp detects a sender wall-clock STEP
                        # between frames: that frame's wall-derived transit
                        # is garbage and is dropped (log once per step).
                        from .runtime import timestamp_utc

                        stamp = (msg.sent_monotonic_ns, msg.sent_wall_ns)
                        jumped = (
                            last_stamp is not None
                            and wall_jump_us(last_stamp, stamp)
                            > WALL_JUMP_TOLERANCE_US
                        )
                        last_stamp = stamp
                        if jumped:
                            log.warning(
                                "authority %d wall clock stepped between "
                                "frames; dropping transit sample", peer,
                            )
                        else:
                            raw_s = (
                                timestamp_utc() - msg.sent_wall_ns / 1e9
                            )
                            rtt_s = connection.latency()
                            if rtt_s == float("inf"):
                                rtt_s = None
                            if self.metrics is not None:
                                self.metrics.dissemination_transit_seconds.labels(
                                    str(peer)
                                ).observe(max(0.0, raw_s))
                            transit = (peer, raw_s, rtt_s)
                    verified = await self._decode_fresh(
                        msg.blocks, transit=transit, peer=peer
                    )
                    verified = [
                        b for b in verified
                        if b.reference.digest not in inflight
                    ]
                    if verified:
                        refs = [b.reference.digest for b in verified]
                        inflight.update(refs)
                        # Awaited in stream order by _accept_ordered, which
                        # observes its exception.  # lint: ignore[task-orphan]
                        fut = asyncio.ensure_future(
                            self._verify_accepted(verified)
                        )
                        try:
                            await pipeline.put((fut, refs))
                        except asyncio.CancelledError:
                            fut.cancel()
                            raise
                elif isinstance(msg, RequestSnapshot):
                    # Serving side: answer a genuinely far-behind peer with
                    # the MANIFEST only (cheap — every connected server may
                    # answer).  The bulk block window ships on an explicit
                    # RequestSnapshotStream from the one peer that adopted
                    # our manifest, so a rejoiner never receives N-1
                    # redundant copies of the whole retained window.
                    manifest = self.core.snapshot_manifest_for(
                        msg.commit_height
                    )
                    if manifest is not None:
                        log.info(
                            "serving snapshot manifest to authority %d (its "
                            "height %d, ours %d)", peer, msg.commit_height,
                            manifest.commit_height,
                        )
                        self._record(
                            "snapshot-served", peer=peer,
                            peer_height=msg.commit_height,
                            height=manifest.commit_height,
                        )
                        snapshot_armed_floor = manifest.gc_round
                        await connection.send(
                            SnapshotResponse(manifest.to_bytes())
                        )
                elif isinstance(msg, RequestSnapshotStream):
                    if (
                        self.parameters.storage.snapshot_catchup
                        and snapshot_armed_floor is not None
                    ):
                        # Serve from the floor we actually advertised (the
                        # peer's value cannot widen the walk), and hold GC
                        # so the window cannot be holed mid-stream.
                        disseminator.stream_snapshot(
                            max(msg.from_round, snapshot_armed_floor),
                            gc_hold=self.core.storage,
                        )
                        snapshot_armed_floor = None
                elif isinstance(msg, SnapshotResponse):
                    await self._handle_snapshot_response(connection, msg)
                elif isinstance(msg, EpochInfo):
                    # Advisory (tag 17): a skewed peer is probably mid-
                    # boundary — never a reason to sever; the committed
                    # sequence itself converges the fleet.
                    self.peer_epochs[peer] = msg.epoch
                    local_epoch = self.core.committee.epoch
                    if msg.epoch != local_epoch:
                        log.warning(
                            "authority %d reports epoch %d (local epoch %d);"
                            " transient skew expected around a boundary",
                            peer, msg.epoch, local_epoch,
                        )
                        self._record(
                            "epoch-skew", peer=peer, peer_epoch=msg.epoch,
                            local_epoch=local_epoch,
                        )
                elif isinstance(msg, RequestBlocks):
                    if self.metrics is not None:
                        self.metrics.block_sync_requests_received.labels(
                            str(peer)
                        ).inc(len(msg.references))
                    await disseminator.send_requested(list(msg.references))
                elif isinstance(msg, BlockNotFound):
                    if self.metrics is not None:
                        self.metrics.block_sync_requests_failed.inc(
                            len(msg.references)
                        )
        finally:
            log.debug("connection to authority %d closed", peer)
            self._record("peer-disconnect", peer=peer)
            # Drain what already entered the pipeline, then stop the acceptor.
            # If this task is itself being cancelled (node stop), don't wait —
            # cancel the acceptor instead of hanging in the finally.
            try:
                await pipeline.put(None)
                await accept_task
            except asyncio.CancelledError:
                accept_task.cancel()
                try:
                    await accept_task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            # Cancel any verify futures still queued (nothing will await
            # them once the acceptor is gone).
            while True:
                try:
                    item = pipeline.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if item is not None:
                    item[0].cancel()
            disseminator.stop()
            self.snapshot_blocks_served += disseminator.snapshot_blocks_sent
            self.snapshot_bytes_served += disseminator.snapshot_bytes_sent
            self._disseminators.pop(peer, None)
            if self.connections.get(peer) is connection:
                del self.connections[peer]
            connection.close()
            # Helper-stream hygiene: relays this peer ran for us died with
            # the connection, and the peer's own blocks now need a relay —
            # ask the surviving peers (within the config caps) both for the
            # peer itself and for every authority it was relaying.
            orphaned = self._helper_subs.drop_helper(peer)
            if (
                self.parameters.synchronizer.disseminate_others_blocks
                and not self._stopped.is_set()
            ):
                self._ask_relays_for(peer)
                for authority in orphaned:
                    live = self.connections.get(authority)
                    if live is None or live.is_closed():
                        self._ask_relays_for(authority)

    async def _handle_snapshot_response(
        self, connection: Connection, msg: SnapshotResponse
    ) -> None:
        """Client side of snapshot catch-up: decode the manifest and adopt
        it on the consensus owner (which also releases any blocks already
        parked on sub-floor parents).  Stale/duplicate manifests — every
        connected peer may answer — are rejected by the owner's gap check;
        only the ADOPTED manifest's sender is asked to stream the bulk
        block window."""
        from .storage import SnapshotManifest

        if not self.parameters.storage.snapshot_catchup:
            # We never asked: an unsolicited manifest with a huge baseline
            # would otherwise poison the commit chain and raise the DAG
            # floor on a node that opted out of catch-up entirely.
            log.warning("ignoring unsolicited snapshot manifest from peer")
            return
        try:
            manifest = SnapshotManifest.from_bytes(msg.manifest)
        except Exception:  # noqa: BLE001 - byzantine peer: drop, don't die
            log.warning("dropping malformed snapshot manifest from peer")
            return
        adopted = await self.dispatcher.apply_snapshot(manifest)
        if adopted:
            log.info(
                "snapshot catch-up adopted: commit height %d, floor %d",
                manifest.commit_height, manifest.gc_round,
            )
            self._record(
                "snapshot-adopted", peer=connection.peer,
                height=manifest.commit_height, floor=manifest.gc_round,
            )
            await connection.send(RequestSnapshotStream(manifest.gc_round))

    def _on_epoch_switch(self, committee, records) -> None:
        """Epoch listener (core.epoch_listeners): runs on the consensus
        owner right after a boundary commit switched the committee.
        Sync-only — retire relay bookkeeping for departed authorities,
        refresh the signature verifier's key view, and re-broadcast our
        new coordinates.  Live connections to departed peers are NOT
        severed: in-flight catch-up streams finish naturally."""
        for authority in range(len(committee)):
            if authority == self.core.authority:
                continue
            if not committee.is_active(authority):
                # A departed authority needs no relays (its blocks are
                # settled history) and must not serve as one of ours.
                self._helper_subs.drop_authority(authority)
                self._helper_subs.drop_helper(authority)
            elif self.parameters.synchronizer.disseminate_others_blocks:
                # A JOINING authority we cannot reach directly yet gets
                # relays immediately — its first own blocks matter (they
                # un-stall its leader slots under the new stake table).
                live = self.connections.get(authority)
                if live is None or live.is_closed():
                    self._ask_relays_for(authority)
        note = getattr(self.block_verifier, "note_committee", None)
        if note is not None:
            note(committee)
        if self.parameters.reconfig and self.core.reconfig is not None:
            info = EpochInfo(committee.epoch, self.core.reconfig.digest())
            for conn in list(self.connections.values()):
                if not conn.is_closed():
                    conn.try_send(info)

    def _ask_relays_for(self, authority: int) -> None:
        """Ask connected peers to relay ``authority``'s blocks (its direct
        connection just dropped), up to maximum_helpers_per_authority."""
        if not self.core.committee.is_active(authority):
            return  # departed this epoch: its blocks are settled history
        last_seen = self.core.block_store.last_seen_by_authority(authority)
        for helper, conn in list(self.connections.items()):
            if helper == authority or conn.is_closed():
                continue
            if not self._helper_subs.may_ask(authority, helper):
                continue
            if conn.try_send(SubscribeOthersFrom(authority, last_seen)):
                self._helper_subs.note_asked(authority, helper)
                self._record("helper-ask", authority=authority, helper=helper)

    async def _request_helper_streams(self, connection: Connection) -> None:
        """On a fresh connection: ask it to relay every authority we have
        no live connection to (late joiner against a partitioned mesh, a
        peer behind an asymmetric fault), within the config caps."""
        for authority in range(len(self.core.committee)):
            if authority in (self.core.authority, connection.peer):
                continue
            if not self.core.committee.is_active(authority):
                continue  # departed this epoch: no relay needed
            live = self.connections.get(authority)
            if live is not None and not live.is_closed():
                continue
            if not self._helper_subs.may_ask(authority, connection.peer):
                continue
            last_seen = self.core.block_store.last_seen_by_authority(authority)
            await connection.send(SubscribeOthersFrom(authority, last_seen))
            self._helper_subs.note_asked(authority, connection.peer)
            self._record(
                "helper-ask", authority=authority, helper=connection.peer
            )

    async def _accept_ordered(
        self, pipeline: asyncio.Queue, connection, inflight: Set[bytes]
    ) -> None:
        while True:
            item = await pipeline.get()
            if item is None:
                return
            fut, refs = item
            try:
                accepted = await fut
                if accepted:
                    await self._add_accepted(accepted, connection)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - a bad batch must not kill the pipe
                log.exception("accept pipeline stage failed")
            finally:
                for ref in refs:
                    inflight.discard(ref)

    # -- the receive pipeline (net_sync.rs:314-386), three stages --
    #
    # Ingest batching invariant (audited for the broadcast-once plane, and
    # pinned by the whole-frame census test): a frame of K blocks crosses
    # the core owner exactly TWICE — one `processed()` dedup command for
    # the whole batch and one `add_blocks()` for the accepted batch.
    # Nothing in this pipeline may hop to the owner per block; a regression
    # here multiplies the owner queue by the frame size at saturation.

    def _count_invalid(self, authority, reason: str, count: int = 1) -> None:
        """Invalid-block attribution (docs/adversary.md): a rejection used
        to vanish into a log line — now every one lands on
        ``mysticeti_invalid_blocks_total{authority, reason}`` and in the
        flight-recorder ring, so a misbehaving peer is attributable from
        /health and fleetmon."""
        if self.metrics is not None:
            self.metrics.mysticeti_invalid_blocks_total.labels(
                str(authority), reason
            ).inc(count)
        self._record(
            "invalid-block", authority=authority, reason=reason, count=count
        )

    async def _decode_fresh(
        self, serialized_blocks, transit=None, peer=None
    ) -> List[StatementBlock]:
        """Stage 1 (host, fast): parse, dedup via the core task, consensus-
        rule checks.  ``transit`` is ``(src peer, raw signed transit s,
        rtt s or None)`` when the frame rode the timestamp extension — each
        fresh block then gets a ``transit`` span whose args carry the link
        and the raw value for the fleet merger's skew estimator.  ``peer``
        attributes malformed payloads (undecodable bytes name no author —
        the DELIVERING connection is the misbehaving party)."""
        tracer = spans.active()
        t_recv = tracer.now() if tracer is not None else 0.0
        timer = self._utilization_timer
        offload = self.dataplane_offload
        if offload is not None and offload.should_offload(
            sum(len(raw) for raw in serialized_blocks)
        ):
            # Big batch + native extension + real node: decode all blocks
            # and hash all digests/signature-prehashes on the offload
            # worker, one GIL round-trip for the whole frame; the event
            # loop keeps scheduling meanwhile.  Stage time lands on
            # utilization_timer{proc="offload:decode"} (measured in the
            # worker) rather than net:decode.  Sims never take this branch
            # (offload inactive) — the inline path below introduces no new
            # awaits, keeping seeded schedules byte-identical.
            decoded = await offload.run(
                "decode", StatementBlock.from_bytes_many, serialized_blocks
            )
        else:
            with timer("net:decode"):
                decoded = StatementBlock.from_bytes_many(serialized_blocks)
        blocks: List[StatementBlock] = [b for b in decoded if b is not None]
        malformed = len(decoded) - len(blocks)
        if malformed:
            log.warning("dropping %d malformed block payload(s) from peer",
                        malformed)
            if peer is not None:
                self._count_invalid(peer, "malformed", malformed)
        if not blocks:
            return []
        # Dedup through the core task before paying for verification.
        processed = await self.dispatcher.processed([b.reference for b in blocks])
        fresh = [b for b, done in zip(blocks, processed) if not done]
        verified: List[StatementBlock] = []
        with timer("net:verify_structure"):
            for block in fresh:
                try:
                    # Epoch-matched structural rules: a pre-boundary block's
                    # threshold clock is judged by its OWN epoch's quorum
                    # (committee_for_epoch falls back to the current
                    # committee outside reconfiguration).
                    block.verify_structure(
                        self.core.committee_for_epoch(block.epoch)
                    )
                except VerificationError as exc:
                    log.warning("rejecting block %r: %s", block.reference, exc)
                    self._count_invalid(block.author(), "structure")
                    continue
                verified.append(block)
        if self.metrics is not None and verified:
            # Proposal-to-receipt per author (metrics.rs:81
            # block_receive_latency) — per block, so the cost scales with
            # block rate, not tx rate.
            from .runtime import timestamp_utc

            now = timestamp_utc()
            for block in verified:
                created = block.meta_creation_time_ns
                if created:
                    self.metrics.block_receive_latency.labels(
                        str(block.author())
                    ).observe(max(0.0, now - created / 1e9))
        if tracer is not None:
            if transit is not None and verified:
                src, raw_s, rtt_s = transit
                extra = {"src": src, "raw_us": int(round(raw_s * 1e6))}
                if rtt_s is not None:
                    extra["rtt_us"] = int(round(rtt_s * 1e6))
                t0_transit = t_recv - max(0.0, raw_s)
                for block in verified:
                    tracer.record_span(
                        "transit", block.reference, t0_transit, t1=t_recv,
                        authority=self.core.authority, extra=extra,
                    )
            for block in verified:
                tracer.record_span(
                    "receive", block.reference, t_recv,
                    authority=self.core.authority,
                )
        return verified

    async def _verify_accepted(
        self, verified: List[StatementBlock]
    ) -> List[StatementBlock]:
        """Stage 2 (accelerator): signature + application check through the
        pluggable verifier (batched across connections on TPU)."""
        tracer = spans.active()
        t_verify = tracer.now() if tracer is not None else 0.0
        results = await self.block_verifier.verify_blocks(verified)
        accepted = [b for b, ok in zip(verified, results) if ok]
        if tracer is not None:
            for block in accepted:
                tracer.record_span(
                    "verify", block.reference, t_verify,
                    authority=self.core.authority,
                )
        if len(accepted) < len(verified):
            log.warning(
                "block verifier rejected %d of %d blocks",
                len(verified) - len(accepted),
                len(verified),
            )
            rejected_by_author: Dict[int, int] = {}
            for block, ok in zip(verified, results):
                if not ok:
                    author = block.author()
                    rejected_by_author[author] = (
                        rejected_by_author.get(author, 0) + 1
                    )
            for author in sorted(rejected_by_author):
                self._count_invalid(
                    author, "signature", rejected_by_author[author]
                )
        return accepted

    async def _add_accepted(self, accepted: List[StatementBlock], origin) -> None:
        """Stage 3: hand to the core, chase missing causal history."""
        tracer = spans.active()
        if tracer is not None:
            # Closed by Core.add_blocks when the block is actually inserted,
            # so the span covers the core-task queue AND any time parked on
            # missing parents.
            t = tracer.now()
            for block in accepted:
                tracer.begin_span(
                    "dag_add", block.reference,
                    authority=self.core.authority, t=t,
                )
        missing = await self.dispatcher.add_blocks(
            accepted, self.connected_authorities.copy()
        )
        if accepted and any(
            d.relay_serving for d in self._disseminators.values()
        ):
            # Freshly stored peer blocks must reach our relay subscribers
            # NOW — their next chance is our own next proposal, a round too
            # late for a parked child.  No-op when nothing was ever relayed
            # (the production-default clean path), and gated on the batch
            # actually carrying a RELAYED author — waking every stream per
            # honest batch is a quadratic wake storm under attack.
            served = set()
            for d in self._disseminators.values():
                if d.relay_serving:
                    served.update(d.relayed_authorities())
            if any(block.author() in served for block in accepted):
                self.signals.new_block_ready()
        if origin is not None and self._fetch_gap_by_author:
            # A direct own-block delivery clears the author's silence score
            # (an honest-but-jittery peer must never accumulate one).
            for block in accepted:
                if block.author() == origin.peer:
                    self._fetch_gap_by_author.pop(origin.peer, None)
                    self.core.content_silent.discard(origin.peer)
                    break
        if self.metrics is not None:
            from .runtime import timestamp_utc

            now = timestamp_utc()
            for block in accepted:
                created = block.meta_creation_time_ns
                if created:
                    self.metrics.add_block_latency.labels(
                        str(block.author())
                    ).observe(max(0.0, now - created / 1e9))
        if missing:
            if self.parameters.synchronizer.disseminate_others_blocks:
                self._score_missing(missing, origin)
            # Request missing causal history from the connection that
            # delivered the children — it is the peer most likely to have the
            # parents (net_sync.rs:276,388-399).  If that connection is stale
            # (replaced after a reconnect) or the send fails, fall back to any
            # live peer so the request is never silently dropped.
            request = RequestBlocks(tuple(missing[:50]))
            sent = False
            if origin is not None and self.connections.get(origin.peer) is origin:
                sent = origin.try_send(request)
            if not sent:
                for peer, conn in list(self.connections.items()):
                    if conn.try_send(request):
                        break

    # Missing-parent fetches tolerated for one author (with a LIVE direct
    # connection and no direct own-block delivery in between) before its
    # relay streams arm: low enough that a withholder costs a handful of
    # rounds, high enough that ordinary delivery jitter never trips it.
    CONTENT_SILENCE_FETCHES = 5

    def _score_missing(self, missing, origin) -> None:
        """Adversary-shaped gap scoring on the fetch path (two shapes):

        * **equivocation-shaped** — the store already holds a DIFFERENT
          digest at the missing reference's (authority, round): some peer
          included a sibling we were never sent.  One relay subscription
          makes every future variant arrive proactively instead of one
          pull round-trip per round.
        * **content silence** — repeated gaps for an author whose direct
          connection is alive but never delivers its own blocks (the
          withholder).  Past :data:`CONTENT_SILENCE_FETCHES`, arm relays
          exactly as if the connection had dropped.

        The relay is asked of ``origin`` first — the peer whose blocks
        referenced the missing digest PROVABLY stores it (an equivocation
        variant lives only on the subset the adversary favored with it;
        a blind helper pick would relay the copy we already hold)."""
        store = self.core.block_store
        for ref in missing:
            author = ref.authority
            if author == self.core.authority:
                continue
            if store.block_exists_at_authority_round(author, ref.round):
                self._record(
                    "equivocation-gap", authority=author, round=ref.round
                )
                self._ask_relay_of(author, origin)
                continue
            score = self._fetch_gap_by_author.get(author, 0) + 1
            self._fetch_gap_by_author[author] = score
            # >= with the content_silent set as the armed flag: an `==`
            # one-shot would disarm FOREVER if the connection happened to
            # be mid-reconnect at the exact threshold fetch.
            if (
                score >= self.CONTENT_SILENCE_FETCHES
                and author not in self.core.content_silent
            ):
                conn = self.connections.get(author)
                if conn is not None and not conn.is_closed():
                    self._record("content-silent", authority=author)
                    # Stop gating proposals on this author's leader slots
                    # too (core.ready_new_block): its blocks now arrive via
                    # relays — waiting for the relay hop on every one of
                    # its slots is the withholder's remaining tax.
                    self.core.content_silent.add(author)
                    self._ask_relay_of(author, origin)

    def _ask_relay_of(self, authority: int, origin) -> None:
        """Subscribe to ``origin``'s relay of ``authority``'s blocks
        (falling back to the blind helper pick when the origin is gone),
        within the same per-authority/total caps as drop-triggered asks."""
        if (
            origin is not None
            and origin.peer != authority
            and self.connections.get(origin.peer) is origin
            and self._helper_subs.may_ask(authority, origin.peer)
        ):
            last_seen = self.core.block_store.last_seen_by_authority(authority)
            if origin.try_send(SubscribeOthersFrom(authority, last_seen)):
                self._helper_subs.note_asked(authority, origin.peer)
                self._record(
                    "helper-ask", authority=authority, helper=origin.peer
                )
                return
        self._ask_relays_for(authority)

    # -- background tasks --

    async def _leader_timeout_task(self) -> None:
        """net_sync.rs:401-444: force a proposal if the round stalls.

        The task must outlive individual command failures: it is the
        liveness backstop, and an exception escaping this loop would
        silently remove the fleet's only stall-recovery mechanism."""
        timeout = self.parameters.leader_timeout_s
        while True:
            waiter = self.signals.round_notify.subscribe()
            round_at_start = self.signals.current_round
            try:
                await asyncio.wait_for(waiter.wait(), timeout=timeout)
            except asyncio.TimeoutError:
                if self.core.epoch_closed():
                    continue
                log.debug(
                    "leader timeout at round %d: forcing proposal", round_at_start
                )
                self._record("leader-timeout", round=round_at_start)
                try:
                    await self.dispatcher.force_new_block(
                        round_at_start + 1, self.connected_authorities.copy()
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("forced proposal failed; timeout task lives on")

    async def _epoch_watch_task(self) -> None:
        """Epoch-aware shutdown (net_sync.rs:466-494): once the epoch is SAFE
        TO CLOSE, keep serving for the grace period (so slower peers can reach
        the epoch-close quorum from our blocks), then stop the node."""
        while not self.core.epoch_closed():
            await asyncio.sleep(0.2)
        grace = self.parameters.shutdown_grace_period_s
        log.info(
            "epoch safe to close at round %d; shutting down after %.1fs grace",
            self.signals.current_round,
            grace,
        )
        await asyncio.sleep(grace)
        await self.stop()

    async def _cleanup_task(self) -> None:
        while True:
            await asyncio.sleep(CLEANUP_INTERVAL_S)
            if self.parameters.enable_cleanup:
                await self.dispatcher.cleanup()
