"""Supervised task spawning: the compliant spawner for lint rule
``task-orphan``.

``asyncio.ensure_future``/``create_task`` hand back a handle that silently
swallows any exception nobody retrieves: a crashed pump, accept loop, or
flush task disappears until interpreter shutdown ("Task exception was never
retrieved"), long after the damage.  ``spawn_logged`` attaches an
exception-logging done-callback at the spawn site so every background task
failure surfaces in the node's log the moment it happens.

Use this for every task whose handle is only stored for later ``cancel()``
(task lists, per-object handles).  Tasks that are *awaited* — where the
awaiter observes the exception — should keep using ``ensure_future``
directly, with an inline ``# lint: ignore[task-orphan]`` naming the awaiter.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional


def spawn_logged(
    coro: Coroutine,
    log: logging.Logger,
    name: Optional[str] = None,
) -> asyncio.Task:
    """Spawn ``coro`` with an exception-logging done-callback.

    Cancellation is the normal shutdown path for supervised background tasks
    and is not logged.  The task handle is returned for ``cancel()``; callers
    need not (and usually do not) await it.
    """
    label = name or getattr(coro, "__qualname__", None) or repr(coro)
    task = asyncio.ensure_future(coro)

    def _log_failure(t: asyncio.Task) -> None:
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            log.error(
                "background task %s crashed: %r", label, exc, exc_info=exc
            )

    task.add_done_callback(_log_failure)
    return task


__all__ = ["spawn_logged"]
