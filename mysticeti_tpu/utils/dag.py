"""Textual DAG DSL for tests — parity with ``Dag::draw`` (types.rs:766-867).

Grammar:  ``"A1 : [A0, B0, C0]; B1 : [A0, B0, C0]"`` — semicolon-separated blocks,
each ``<Authority letter><round> : [<includes>]``.  Authority letters map A→0, B→1, …
References to round-0 names resolve to genesis blocks, which are created implicitly.

Unlike the reference (whose cfg(test) crypto is stubbed to zero digests,
crypto.rs:63-75), blocks built here carry real blake2b digests and dummy signatures,
so the DSL builds blocks in topological order and resolves names to real references.
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

from ..types import BaseStatement, BlockReference, StatementBlock

_BLOCK_RE = re.compile(r"^\s*([A-Z])(\d+)\s*:\s*\[(.*)\]\s*$")
_REF_RE = re.compile(r"^\s*([A-Z])(\d+)\s*$")


def _name(authority: int, round_: int) -> str:
    return f"{chr(ord('A') + authority)}{round_}"


class Dag:
    """A named collection of blocks built from the DSL (types.rs:774-867)."""

    def __init__(self, blocks: Dict[str, StatementBlock]) -> None:
        self.blocks = blocks

    @classmethod
    def draw(cls, s: str) -> "Dag":
        specs: List[Tuple[int, int, List[Tuple[int, int]]]] = []
        for part in s.split(";"):
            if not part.strip():
                continue
            m = _BLOCK_RE.match(part)
            if not m:
                raise ValueError(f"bad DSL block: {part!r}")
            authority = ord(m.group(1)) - ord("A")
            round_ = int(m.group(2))
            includes: List[Tuple[int, int]] = []
            body = m.group(3).strip()
            if body:
                for ref in body.split(","):
                    rm = _REF_RE.match(ref)
                    if not rm:
                        raise ValueError(f"bad DSL reference: {ref!r}")
                    includes.append((ord(rm.group(1)) - ord("A"), int(rm.group(2))))
            specs.append((authority, round_, includes))

        built: Dict[str, StatementBlock] = {}

        def ensure(authority: int, round_: int) -> BlockReference:
            name = _name(authority, round_)
            if name in built:
                return built[name].reference
            if round_ == 0:
                blk = StatementBlock.new_genesis(authority)
                built[name] = blk
                return blk.reference
            raise ValueError(f"DSL reference to undefined non-genesis block {name}")

        # Build in round order so includes resolve to already-built blocks.
        for authority, round_, includes in sorted(specs, key=lambda t: t[1]):
            refs = [ensure(a, r) for a, r in includes]
            blk = StatementBlock.build(authority, round_, refs, ())
            built[_name(authority, round_)] = blk
        return cls(built)

    @classmethod
    def draw_block(cls, s: str) -> StatementBlock:
        """Build a single block whose includes may reference genesis blocks."""
        dag = cls.draw(s)
        m = _BLOCK_RE.match(s.split(";")[0])
        assert m is not None
        return dag.blocks[_name(ord(m.group(1)) - ord("A"), int(m.group(2)))]

    def __getitem__(self, name: str) -> StatementBlock:
        return self.blocks[name]

    def all_blocks(self) -> List[StatementBlock]:
        return list(self.blocks.values())

    def __len__(self) -> int:
        return len(self.blocks)
