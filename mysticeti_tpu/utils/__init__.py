from .tasks import spawn_logged

__all__ = ["spawn_logged"]
