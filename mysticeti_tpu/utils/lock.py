"""Monitored lock: measures wait/hold time per labeled section.

Capability parity with ``mysticeti-core/src/lock.rs`` (:9-41) — an
instrumented lock that *measures* contention rather than preventing it.  The
single-owner core-task design means consensus state needs no lock at all
(core_task.py); this exists for auxiliary shared state (and, like the
reference's, mostly as an observability tool).
"""
from __future__ import annotations

import asyncio
import time
from typing import Optional


class MonitoredLock:
    """asyncio.Lock wrapper feeding utilization-timer metrics.

    Usage::

        lock = MonitoredLock("block_cache", metrics)
        async with lock:
            ...
    """

    def __init__(self, name: str, metrics=None) -> None:
        self.name = name
        self.metrics = metrics
        self._lock = asyncio.Lock()
        self._acquired_at = 0.0
        self.wait_total_s = 0.0
        self.hold_total_s = 0.0

    async def __aenter__(self) -> "MonitoredLock":
        start = time.monotonic()
        await self._lock.acquire()
        waited = time.monotonic() - start
        self.wait_total_s += waited
        self._acquired_at = time.monotonic()
        if self.metrics is not None:
            self.metrics.utilization_timer_us.labels(
                f"lock_wait/{self.name}"
            ).inc(int(waited * 1e6))
        return self

    async def __aexit__(self, *exc) -> None:
        held = time.monotonic() - self._acquired_at
        self.hold_total_s += held
        if self.metrics is not None:
            self.metrics.utilization_timer_us.labels(
                f"lock_hold/{self.name}"
            ).inc(int(held * 1e6))
        self._lock.release()
