"""Deterministic chaos engine: seeded fault injection over the virtual-time sim.

The reproduction's headline claim (arXiv 2310.14821) is that commits stay
safe and low-latency under crash faults and partitions — but the sim tier
only exercised static partition/heal, and the WAL recovery path
(``core.py``/``validator.init_storage``) was never driven mid-simulation.
This module closes that gap with four pieces:

* :class:`FaultPlan` — a declarative, JSON-serializable plan: per-link
  message drop/duplicate/delay probabilities (:class:`LinkFault`), timed
  (a)symmetric partitions (:class:`PartitionFault`), and crash-restarts of
  whole validators (:class:`CrashFault`), optionally with a torn WAL tail.
* :class:`ChaosEngine` — executes a plan against a fleet on the
  :class:`~mysticeti_tpu.runtime.simulated.DeterministicLoop`.  The timed
  schedule is resolved up-front from the plan alone (:func:`resolve_schedule`)
  and per-message coin flips come from a dedicated ``random.Random`` seeded by
  the plan, so a same-seed re-run produces a byte-identical fault schedule
  AND byte-identical fault log (:meth:`ChaosEngine.fault_log_bytes`).
* :class:`SafetyChecker` — cross-node, cross-restart commit auditor: every
  committed sub-dag is recorded by (authority, height); two anchors at the
  same height — on different nodes, or on one node before and after a
  crash — raise :class:`SafetyViolation` the moment they are observed.
* :class:`ChaosSimHarness` — an N-validator fleet over
  :class:`~mysticeti_tpu.simulated_network.SimulatedNetwork` whose per-node
  WALs survive crash-restart: :meth:`ChaosSimHarness.restart` rebuilds the
  validator from the SAME WAL path, driving the full
  ``BlockStore.open`` -> ``Core`` recovery path under fire.

Partitions are injected as directed BLACKHOLES (messages dropped while the
connections stay up) rather than severed links: that is the nastier fault —
no closure event tells either side anything happened — and it composes
cleanly with concurrent crashes and asymmetric (one-way) cuts.  The severed
flavor remains available directly on ``SimulatedNetwork.partition``.

``mysticeti-tpu chaos --plan plan.json`` replays a plan from JSON (cli.py);
``docs/fault-injection.md`` documents the schema and guarantees.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import os
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .adversary import AdversaryEngine, AdversarySpec
from .block_handler import TestBlockHandler
from .commit_observer import TestCommitObserver
from .committee import Committee
from .config import Parameters
from .core import Core, CoreOptions
from .flight_recorder import FlightRecorder
from .health import FleetHealthMonitor, HealthProbe, SLOThresholds
from .metrics import Metrics
from .net_sync import NetworkSyncer
from .simulated_network import SimulatedNetwork
from .tracing import logger
from .types import BlockReference, Share
from .utils.tasks import spawn_logged

log = logger(__name__)


# ---------------------------------------------------------------------------
# Fault plan (declarative, JSON round-trippable)


@dataclass(frozen=True)
class LinkFault:
    """Probabilistic per-message faults on matching links inside a window.

    ``src``/``dst`` of ``None`` match any sender/receiver; ``end_s`` of
    ``None`` means "until the end of the run".  The first matching fault in
    plan order wins for a given (src, dst, t).  ``duplicate_p`` re-delivers a
    copy after an extra ``delay_extra_s`` draw — duplicates are always late
    (an on-time duplicate is indistinguishable from the original in-order
    delivery and would test nothing).
    """

    drop_p: float = 0.0
    duplicate_p: float = 0.0
    delay_p: float = 0.0
    delay_extra_s: Tuple[float, float] = (0.05, 0.25)
    src: Optional[int] = None
    dst: Optional[int] = None
    start_s: float = 0.0
    end_s: Optional[float] = None

    def matches(self, src: int, dst: int, t: float) -> bool:
        if self.src is not None and self.src != src:
            return False
        if self.dst is not None and self.dst != dst:
            return False
        if t < self.start_s:
            return False
        return self.end_s is None or t < self.end_s

    def to_dict(self) -> dict:
        return {
            "drop_p": self.drop_p,
            "duplicate_p": self.duplicate_p,
            "delay_p": self.delay_p,
            "delay_extra_s": list(self.delay_extra_s),
            "src": self.src,
            "dst": self.dst,
            "start_s": self.start_s,
            "end_s": self.end_s,
        }

    @staticmethod
    def from_dict(d: dict) -> "LinkFault":
        return LinkFault(
            drop_p=float(d.get("drop_p", 0.0)),
            duplicate_p=float(d.get("duplicate_p", 0.0)),
            delay_p=float(d.get("delay_p", 0.0)),
            delay_extra_s=tuple(d.get("delay_extra_s", (0.05, 0.25))),
            src=d.get("src"),
            dst=d.get("dst"),
            start_s=float(d.get("start_s", 0.0)),
            end_s=None if d.get("end_s") is None else float(d["end_s"]),
        )


@dataclass(frozen=True)
class PartitionFault:
    """Timed blackhole partition between two groups.

    ``symmetric=True`` drops both directions; ``False`` drops only
    ``group_a -> group_b`` (the asymmetric cut: A's blocks vanish while A
    still hears everything — the failure mode static partition tests miss).
    """

    start_s: float
    end_s: float
    group_a: Tuple[int, ...]
    group_b: Tuple[int, ...]
    symmetric: bool = True

    def directed_pairs(self) -> List[Tuple[int, int]]:
        pairs = [(a, b) for a in self.group_a for b in self.group_b]
        if self.symmetric:
            pairs += [(b, a) for a in self.group_a for b in self.group_b]
        return sorted(set(pairs))

    def to_dict(self) -> dict:
        return {
            "start_s": self.start_s,
            "end_s": self.end_s,
            "group_a": list(self.group_a),
            "group_b": list(self.group_b),
            "symmetric": self.symmetric,
        }

    @staticmethod
    def from_dict(d: dict) -> "PartitionFault":
        return PartitionFault(
            start_s=float(d["start_s"]),
            end_s=float(d["end_s"]),
            group_a=tuple(int(a) for a in d["group_a"]),
            group_b=tuple(int(b) for b in d["group_b"]),
            symmetric=bool(d.get("symmetric", True)),
        )


@dataclass(frozen=True)
class CrashFault:
    """Crash-restart of a whole validator.

    At ``at_s`` the node's links break, its tasks are torn down, and its WAL
    is closed; ``downtime_s`` later it is rebuilt FROM THE SAME WAL via the
    ``BlockStore.open`` recovery path and rejoins the fleet.
    ``torn_tail_bytes > 0`` truncates that many bytes off the WAL after the
    crash, simulating a tear mid-entry (loss of the last un-synced write):
    replay must stop cleanly at the tear and recovery truncates the torn
    bytes before the first new append.
    """

    node: int
    at_s: float
    downtime_s: float
    torn_tail_bytes: int = 0

    def to_dict(self) -> dict:
        return {
            "node": self.node,
            "at_s": self.at_s,
            "downtime_s": self.downtime_s,
            "torn_tail_bytes": self.torn_tail_bytes,
        }

    @staticmethod
    def from_dict(d: dict) -> "CrashFault":
        return CrashFault(
            node=int(d["node"]),
            at_s=float(d["at_s"]),
            downtime_s=float(d["downtime_s"]),
            torn_tail_bytes=int(d.get("torn_tail_bytes", 0)),
        )


@dataclass
class FaultPlan:
    """The whole declarative scenario; ``seed`` drives BOTH the simulator's
    loop RNG and the engine's per-message fault draws.  ``adversaries``
    (adversary.py) declares Byzantine behavior alongside the benign faults
    — one plan, one seed, one byte-identical schedule."""

    seed: int = 0
    link_faults: List[LinkFault] = field(default_factory=list)
    partitions: List[PartitionFault] = field(default_factory=list)
    crashes: List[CrashFault] = field(default_factory=list)
    adversaries: List[AdversarySpec] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "link_faults": [f.to_dict() for f in self.link_faults],
            "partitions": [p.to_dict() for p in self.partitions],
            "crashes": [c.to_dict() for c in self.crashes],
            "adversaries": [a.to_dict() for a in self.adversaries],
        }

    def to_json(self) -> str:
        return _canonical_json(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "FaultPlan":
        return FaultPlan(
            seed=int(d.get("seed", 0)),
            link_faults=[LinkFault.from_dict(f) for f in d.get("link_faults", [])],
            partitions=[
                PartitionFault.from_dict(p) for p in d.get("partitions", [])
            ],
            crashes=[CrashFault.from_dict(c) for c in d.get("crashes", [])],
            adversaries=[
                AdversarySpec.from_dict(a) for a in d.get("adversaries", [])
            ],
        )

    @staticmethod
    def from_json(text: str) -> "FaultPlan":
        return FaultPlan.from_dict(json.loads(text))


def _canonical_json(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def resolve_schedule(plan: FaultPlan) -> List[dict]:
    """The plan's timed events, resolved up-front from the plan ALONE.

    Purely a function of the plan (no RNG, no sim state), so it is trivially
    byte-identical across runs — the determinism the fault LOG then extends
    to the per-message draws.  Events at equal times keep a stable total
    order via their sequence number.
    """
    events: List[dict] = []
    for p in plan.partitions:
        events.append(
            {"t": p.start_s, "kind": "partition_start", **p.to_dict()}
        )
        events.append({"t": p.end_s, "kind": "partition_end", **p.to_dict()})
    for c in plan.crashes:
        events.append({"t": c.at_s, "kind": "crash", **c.to_dict()})
        events.append(
            {"t": c.at_s + c.downtime_s, "kind": "restart", "node": c.node}
        )
    events.sort(key=lambda e: (e["t"], e["kind"], _canonical_json(e)))
    for seq, event in enumerate(events):
        event["seq"] = seq
    return events


def schedule_bytes(plan: FaultPlan) -> bytes:
    return _canonical_json(resolve_schedule(plan)).encode()


# ---------------------------------------------------------------------------
# Safety checker


class SafetyViolation(AssertionError):
    """Two different leader anchors committed at the same height."""


class SafetyChecker:
    """Cross-node, cross-restart commit auditor.

    Commits are recorded by (authority, height) as they happen — including
    re-observations after a WAL-replay restart, which MUST agree with what
    the node committed before crashing.  :meth:`check` then asserts the
    global invariant: at every height, all nodes that committed it committed
    the same anchor (prefix consistency of committed leader sequences).
    """

    def __init__(self) -> None:
        self._anchors: Dict[int, Dict[int, BlockReference]] = {}
        # Snapshot catch-up: per-authority adopted baseline height.  Heights
        # inside the adopted prefix were committed by the FLEET while the
        # node was away — a gap wholly below the baseline is the expected
        # catch-up shape, not a linearizer-order violation.  The adopted
        # anchor itself is recorded, so cross-node consistency still covers
        # the baseline height.
        self._adopted: Dict[int, int] = {}
        # First mid-run violation, re-raised by check(): an observe() raise
        # inside a node's accept pipeline is logged there, not propagated,
        # so the end-of-run audit must still fail the scenario.
        self._violation: Optional[SafetyViolation] = None
        # Byzantine scenarios (adversary.py): declared adversaries are
        # excluded from the HONEST consistency invariant — a node that
        # actively lies forfeits its own-commit guarantees — and any
        # divergence that involves one is recorded here, attributed by
        # name, instead of failing the scenario.  Honest-honest divergence
        # still raises: that is the safety property under attack.
        self.adversaries: Set[int] = set()
        self.adversary_divergence: List[dict] = []
        # Epoch reconfiguration (reconfig.py): per-authority epoch records
        # — epoch -> (boundary_height, digest).  All honest nodes that
        # cross a boundary must derive the SAME boundary for the same
        # epoch; a disagreement is a safety violation of the same class as
        # a commit fork (the committees diverge, then everything does).
        self._epochs: Dict[int, Dict[int, Tuple[int, bytes]]] = {}
        # Execution plane (execution.py): per-authority state-root chain —
        # height -> chained root after folding that commit.  Every honest
        # node must derive the SAME root at every shared height; a
        # disagreement means the replicated state machine diverged — the
        # exact failure class execution-backed finality exists to rule out,
        # and strictly stronger evidence than an anchor fork (same inputs,
        # different outputs).
        self._state_roots: Dict[int, Dict[int, bytes]] = {}
        # Committed-throughput accounting: transactions (Share statements)
        # AND blocks in each node's committed sub-dags, keyed observer ->
        # block author, counted once per height (a WAL-replay
        # re-observation of an already-recorded height adds nothing).
        # Per-author so the scenario matrix can compare HONEST-AUTHORED
        # throughput against the clean twin — a Byzantine node's own
        # unsequenced load is its own loss, not a liveness failure.
        # Blocks are the liveness gate's unit: the sim's TestBlockHandler
        # mints one Share per handle_blocks BATCH, so relayed/fetched
        # delivery (which coalesces batches) under attack suppresses load
        # GENERATION — a generator artifact the block count is blind to.
        self.committed_tx: Dict[int, Dict[int, int]] = {}
        self.committed_blocks: Dict[int, Dict[int, int]] = {}

    def mark_adversary(self, authority: int) -> None:
        self.adversaries.add(authority)

    def _note_adversary_divergence(self, **fields) -> None:
        self.adversary_divergence.append(dict(fields))
        log.warning("adversary-attributed commit divergence: %s", fields)

    def note_adopted(
        self, authority: int, height: int, leader: Optional[BlockReference]
    ) -> None:
        """The authority adopted a snapshot baseline at ``height``."""
        self._adopted[authority] = max(self._adopted.get(authority, 0), height)
        if leader is not None and height > 0:
            mine = self._anchors.setdefault(authority, {})
            prev = mine.get(height)
            if prev is not None and prev != leader:
                if authority in self.adversaries:
                    self._note_adversary_divergence(
                        kind="adopt-conflict", adversary=authority,
                        height=height,
                    )
                    mine[height] = leader
                    return
                violation = SafetyViolation(
                    f"authority {authority} adopted anchor {leader!r} at "
                    f"height {height} but had committed {prev!r}"
                )
                if self._violation is None:
                    self._violation = violation
                raise violation
            mine[height] = leader

    def note_epoch(self, authority: int, records) -> None:
        """Record epoch boundaries an authority derived (EpochRecord list
        from a switch, a recovery re-scan, or a snapshot chain adoption).
        A node re-deriving a DIFFERENT boundary for an epoch it already
        crossed — e.g. before and after a crash — raises immediately."""
        mine = self._epochs.setdefault(authority, {})
        for rec in records:
            entry = (rec.boundary_height, bytes(rec.digest))
            prev = mine.get(rec.epoch)
            if prev is not None and prev != entry:
                if authority in self.adversaries:
                    self._note_adversary_divergence(
                        kind="epoch-self-conflict", adversary=authority,
                        epoch=rec.epoch,
                    )
                    mine[rec.epoch] = entry
                    continue
                violation = SafetyViolation(
                    f"authority {authority} derived epoch {rec.epoch} twice "
                    f"with different boundaries: {prev!r} then {entry!r}"
                )
                if self._violation is None:
                    self._violation = violation
                raise violation
            mine[rec.epoch] = entry

    def epoch_of(self, authority: int) -> int:
        mine = self._epochs.get(authority)
        return max(mine) if mine else 0

    def note_state_root(self, authority: int, height: int, root: bytes) -> None:
        """Record the state root an authority derived by folding the commit
        at ``height`` through the execution state machine.  A node
        re-deriving a DIFFERENT root for a height it already executed —
        e.g. across a crash-restart replay or a snapshot adoption — raises
        immediately: determinism broke on ONE node before it could fork
        the fleet."""
        mine = self._state_roots.setdefault(authority, {})
        root = bytes(root)
        prev = mine.get(height)
        if prev is not None and prev != root:
            if authority in self.adversaries:
                self._note_adversary_divergence(
                    kind="state-root-self-conflict", adversary=authority,
                    height=height,
                )
                mine[height] = root
                return
            violation = SafetyViolation(
                f"authority {authority} executed height {height} twice with "
                f"different roots: {prev.hex()[:16]} then {root.hex()[:16]}"
            )
            if self._violation is None:
                self._violation = violation
            raise violation
        mine[height] = root

    def executed_height(self, authority: int) -> int:
        mine = self._state_roots.get(authority)
        return max(mine) if mine else 0

    def state_root_at(self, authority: int, height: int) -> Optional[bytes]:
        return self._state_roots.get(authority, {}).get(height)

    def observe(self, authority: int, committed) -> None:
        """Record a node's freshly committed sub-dags (List[CommittedSubDag])."""
        mine = self._anchors.setdefault(authority, {})
        for commit in committed:
            if commit.height not in mine:
                blocks = getattr(commit, "blocks", None) or ()
                by_author = self.committed_tx.setdefault(authority, {})
                blocks_by_author = self.committed_blocks.setdefault(
                    authority, {}
                )
                for block in blocks:
                    author = block.author()
                    blocks_by_author[author] = (
                        blocks_by_author.get(author, 0) + 1
                    )
                    shares = sum(
                        1 for st in block.statements if isinstance(st, Share)
                    )
                    if shares:
                        by_author[author] = by_author.get(author, 0) + shares
            prev = mine.get(commit.height)
            if prev is not None and prev != commit.anchor:
                if authority in self.adversaries:
                    self._note_adversary_divergence(
                        kind="self-conflict", adversary=authority,
                        height=commit.height,
                    )
                    mine[commit.height] = commit.anchor
                    continue
                violation = SafetyViolation(
                    f"authority {authority} committed two anchors at height "
                    f"{commit.height}: {prev!r} then {commit.anchor!r}"
                )
                if self._violation is None:
                    self._violation = violation
                raise violation
            mine[commit.height] = commit.anchor

    def committed_height(self, authority: int) -> int:
        mine = self._anchors.get(authority)
        return max(mine) if mine else 0

    def sequence(self, authority: int) -> List[BlockReference]:
        """The node's committed anchors in height order; raises on gaps
        (a hole means commits were observed out of linearizer order).  A
        gap lying wholly below the authority's adopted snapshot baseline is
        the legal catch-up shape (see :meth:`note_adopted`)."""
        mine = self._anchors.get(authority, {})
        adopted = self._adopted.get(authority, 0)
        out: List[BlockReference] = []
        expect = 1
        for height in sorted(mine):
            if height != expect and height - 1 > adopted:
                raise SafetyViolation(
                    f"authority {authority} has a commit gap at height "
                    f"{expect} (next observed: {height})"
                )
            out.append(mine[height])
            expect = height + 1
        return out

    def check(self) -> None:
        """Global prefix consistency: same anchor at every shared height.

        With declared adversaries the invariant is audited over HONEST
        nodes (that is the paper's guarantee: safety among the correct
        f+1..n); an adversary node whose own commit stream diverges from
        the honest golden sequence is recorded in
        :attr:`adversary_divergence`, attributed by name — evidence, not a
        scenario failure."""
        if self._violation is not None:
            raise self._violation
        golden: Dict[int, Tuple[BlockReference, int]] = {}
        for authority in sorted(self._anchors):
            if authority in self.adversaries:
                continue
            self.sequence(authority)  # per-node contiguity
            for height, anchor in self._anchors[authority].items():
                prev = golden.get(height)
                if prev is None:
                    golden[height] = (anchor, authority)
                elif prev[0] != anchor:
                    raise SafetyViolation(
                        f"fork at height {height}: authority {prev[1]} "
                        f"committed {prev[0]!r}, authority {authority} "
                        f"committed {anchor!r}"
                    )
        for authority in sorted(self.adversaries & set(self._anchors)):
            try:
                self.sequence(authority)
            except SafetyViolation:
                self._note_adversary_divergence(
                    kind="gap", adversary=authority
                )
            for height, anchor in self._anchors[authority].items():
                prev = golden.get(height)
                if prev is not None and prev[0] != anchor:
                    self._note_adversary_divergence(
                        kind="fork", adversary=authority, height=height,
                    )
        # Epoch-boundary agreement (reconfig.py): every honest node that
        # crossed epoch E derived the same (boundary height, committee
        # digest) — prefix consistency extended across reconfigurations.
        golden_epochs: Dict[int, Tuple[Tuple[int, bytes], int]] = {}
        for authority in sorted(self._epochs):
            if authority in self.adversaries:
                continue
            for epoch, entry in self._epochs[authority].items():
                prev = golden_epochs.get(epoch)
                if prev is None:
                    golden_epochs[epoch] = (entry, authority)
                elif prev[0] != entry:
                    raise SafetyViolation(
                        f"epoch fork at epoch {epoch}: authority {prev[1]} "
                        f"derived {prev[0]!r}, authority {authority} "
                        f"derived {entry!r}"
                    )
        for authority in sorted(self.adversaries & set(self._epochs)):
            for epoch, entry in self._epochs[authority].items():
                prev = golden_epochs.get(epoch)
                if prev is not None and prev[0] != entry:
                    self._note_adversary_divergence(
                        kind="epoch-fork", adversary=authority, epoch=epoch,
                    )
        # Execution state-root agreement (execution.py): every honest node
        # that folded the commit at height H derived the same chained root
        # — identical committed inputs produced identical replicated state.
        # A disagreement here with AGREEING anchors is the worst failure
        # this harness can detect: consensus held, determinism did not.
        golden_roots: Dict[int, Tuple[bytes, int]] = {}
        for authority in sorted(self._state_roots):
            if authority in self.adversaries:
                continue
            for height, root in self._state_roots[authority].items():
                prev = golden_roots.get(height)
                if prev is None:
                    golden_roots[height] = (root, authority)
                elif prev[0] != root:
                    raise SafetyViolation(
                        f"state-root fork at height {height}: authority "
                        f"{prev[1]} derived {prev[0].hex()[:16]}, authority "
                        f"{authority} derived {root.hex()[:16]}"
                    )
        for authority in sorted(self.adversaries & set(self._state_roots)):
            for height, root in self._state_roots[authority].items():
                prev = golden_roots.get(height)
                if prev is not None and prev[0] != root:
                    self._note_adversary_divergence(
                        kind="state-root-fork", adversary=authority,
                        height=height,
                    )


class _CheckedCommitObserver(TestCommitObserver):
    """TestCommitObserver that feeds every commit to the SafetyChecker."""

    def __init__(self, checker: SafetyChecker, authority: int, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._checker = checker
        self._checked_authority = authority

    def handle_commit(self, committed_leaders):
        committed = super().handle_commit(committed_leaders)
        self._checker.observe(self._checked_authority, committed)
        return committed

    def adopt_snapshot(self, manifest):
        super().adopt_snapshot(manifest)
        self._checker.note_adopted(
            self._checked_authority,
            manifest.commit_height,
            manifest.last_committed_leader,
        )


# ---------------------------------------------------------------------------
# Harness: an N-validator sim fleet whose nodes survive crash-restart


class _SimNodeNetwork:
    """Adapter giving NetworkSyncer the TcpNetwork surface over the sim."""

    def __init__(self, queue: asyncio.Queue) -> None:
        self.connections = queue

    async def stop(self) -> None:
        pass


class ChaosSimHarness:
    """N validators over :class:`SimulatedNetwork` with per-node WAL files.

    Unlike the plain sim-test fleets, nodes here are individually crashable:
    :meth:`crash` tears a node down (links break first — a dead node stops
    talking mid-protocol — then tasks, then the WAL), and :meth:`restart`
    rebuilds the validator from the same WAL path, driving the full
    ``BlockStore.open`` -> ``Core`` recovery path, and reconnects it.
    """

    def __init__(
        self,
        n: int,
        wal_dir: str,
        parameters: Optional[Parameters] = None,
        committee: Optional[Committee] = None,
        verifier_factory=None,
        with_metrics: bool = False,
        slo: Optional[SLOThresholds] = None,
        health_interval_s: float = 1.0,
        per_node_parameters: Optional[Dict[int, Parameters]] = None,
        latency_ranges=None,
        adversaries: Optional[Set[int]] = None,
        absent: Optional[Set[int]] = None,
    ) -> None:
        self.n = n
        self.wal_dir = wal_dir
        # Epoch reconfiguration (reconfig.py): ``absent`` authorities are
        # registered in the committee (stable-index membership) but not
        # BUILT at start — :meth:`join` boots one mid-run, typically after
        # a committed ADD change activated its stake; ``retired`` tracks
        # clean departures (:meth:`retire`) so the health plane never
        # flags them as stragglers.
        self.absent: Set[int] = set(absent or ())
        self.retired: Set[int] = set()
        self.committee = committee or Committee.new_test([1] * n)
        self.signers = Committee.benchmark_signers(n)
        self.parameters = parameters or Parameters(leader_timeout_s=1.0)
        # Mixed-version drills (scenarios.py): individual nodes may run
        # with different Parameters (soft wire tags on/off, storage knobs)
        # — exactly the rolling-upgrade skew a real fleet lives through.
        self.per_node_parameters = per_node_parameters or {}
        # (authority, committee, metrics) -> BlockVerifier, or None for the
        # AcceptAll default (chaos scenarios that are not about the verifier
        # keep the sim fully single-threaded, hence bit-reproducible).
        self.verifier_factory = verifier_factory
        # One Metrics per authority, SHARED across restarts, so counters like
        # crash_recovery_total accumulate over the node's whole life.
        self.metrics: List[Optional[Metrics]] = [
            Metrics() if with_metrics else None for _ in range(n)
        ]
        self.checker = SafetyChecker()
        for adversary in sorted(adversaries or ()):
            self.checker.mark_adversary(adversary)
        self.sim_net = SimulatedNetwork(n, latency_ranges=latency_ranges)
        self.nodes: List[Optional[NetworkSyncer]] = [None] * n
        self.down: Set[int] = set()
        # Flight recorders: one ring per authority, SURVIVING restarts like
        # the probes (the forensic window must span the crash) — memory-only
        # here; ``run_chaos_sim`` dumps every live node's ring the moment
        # the SafetyChecker fails.
        self.recorders: Dict[int, FlightRecorder] = {
            a: FlightRecorder(authority=a, metrics=self.metrics[a])
            for a in range(n)
        }
        # Health plane: one probe per authority, SURVIVING restarts (rate
        # state and the alert stream span a node's whole life); a central
        # loop-clocked monitor samples them so same-seed runs produce a
        # byte-identical health timeline.
        self.probes: Dict[int, HealthProbe] = (
            {
                a: HealthProbe(
                    a, n, metrics=self.metrics[a], slo=slo,
                    recorder=self.recorders[a],
                )
                for a in range(n)
            }
            if slo is not None
            else {}
        )
        self.health_monitor: Optional[FleetHealthMonitor] = (
            FleetHealthMonitor(self.probes.get, n, interval_s=health_interval_s)
            if slo is not None
            else None
        )

    def _wal_path(self, authority: int) -> str:
        return os.path.join(self.wal_dir, f"wal-{authority}")

    def parameters_for(self, authority: int) -> Parameters:
        return self.per_node_parameters.get(authority, self.parameters)

    def _build_node(self, authority: int) -> NetworkSyncer:
        from .storage import open_store

        parameters = self.parameters_for(authority)
        recovered, observer_recovered, wal_writer, lifecycle = open_store(
            authority, self._wal_path(authority), self.committee,
            parameters, self.metrics[authority],
        )
        handler = TestBlockHandler(
            last_transaction=authority * 1_000_000,
            committee=self.committee,
            authority=authority,
        )
        core = Core(
            block_handler=handler,
            authority=authority,
            committee=self.committee,
            parameters=parameters,
            recovered=recovered,
            wal_writer=wal_writer,
            options=CoreOptions.test(),
            signer=self.signers[authority],
            metrics=self.metrics[authority],
            storage=lifecycle,
        )
        observer = _CheckedCommitObserver(
            self.checker,
            authority,
            core.block_store,
            self.committee,
            recovered_state=observer_recovered,
        )
        recorder = self.recorders[authority]
        observer.recorder = recorder
        if lifecycle is not None:
            lifecycle.recorder = recorder
        # Equivocation detection (block_store.py) flows to the same ring:
        # a double-proposal observed seconds before a safety incident is
        # exactly the forensic edge the recorder exists for.  Commit-rule
        # decision skips/flips (decisions.py) join it — a Byzantine run's
        # skipped slots arrive pre-explained.
        core.block_store.recorder = recorder
        core.committer.ledger.recorder = recorder
        verifier = (
            self.verifier_factory(
                authority, self.committee, self.metrics[authority]
            )
            if self.verifier_factory is not None
            else None
        )
        node = NetworkSyncer(
            core,
            observer,
            _SimNodeNetwork(self.sim_net.node_connections[authority]),
            parameters=parameters,
            block_verifier=verifier,
            metrics=self.metrics[authority],
            recorder=recorder,
        )
        probe = self.probes.get(authority)
        if probe is not None:
            probe.attach(
                core=core,
                net_syncer=node,
                block_verifier=verifier,
                commit_observer=observer,
            )
        if core.reconfig is not None:
            # Feed the epoch audit: boundaries already re-derived by this
            # boot (recovery re-scan / checkpoint chain), then every future
            # switch via the listener.
            if core.reconfig.chain.records:
                self.checker.note_epoch(authority, core.reconfig.chain.records)
            core.epoch_listeners.append(
                lambda committee, records, a=authority: self.checker.note_epoch(
                    a, records
                )
            )
        if core.execution is not None:
            # Feed the state-root audit: heights already re-folded by this
            # boot (recovery re-scan over the post-checkpoint commits),
            # then every future fold via the listener.  A crash-restarted
            # node thus re-asserts the SAME roots it derived before the
            # crash — the self-conflict arm of note_state_root.
            if core.execution.last_height > 0:
                self.checker.note_state_root(
                    authority, core.execution.last_height, core.execution.root
                )
            core.execution_listeners.append(
                lambda result, a=authority: self.checker.note_state_root(
                    a, result.height, result.root
                )
            )
        return node

    async def start(self) -> None:
        for authority in range(self.n):
            if authority in self.absent:
                self.down.add(authority)
                continue
            node = self._build_node(authority)
            self.nodes[authority] = node
            await node.start()
        await self.sim_net.connect_all()
        for authority in sorted(self.absent):
            # Links to an absent node are severed immediately (peers see
            # closure, exactly like a pre-start crash); join() restores
            # them through the ordinary restart path.
            self.sim_net.crash(authority)
        if self.health_monitor is not None:
            for authority in self.absent:
                self.health_monitor.note_retired(authority)
            self.health_monitor.start()

    async def crash(self, authority: int, torn_tail_bytes: int = 0) -> None:
        node = self.nodes[authority]
        assert node is not None, f"authority {authority} is already down"
        self.down.add(authority)
        self.recorders[authority].record(
            "crash", torn_tail_bytes=torn_tail_bytes
        )
        probe = self.probes.get(authority)
        if probe is not None:
            probe.detach()  # sampled as {"down": true} until restart
        self.sim_net.crash(authority)
        await node.stop()
        # Close the WAL cleanly (drains the async appender): the baseline
        # crash model is "durable up to the last acknowledged append".  The
        # torn tail below then simulates the STRONGER loss — a write cut
        # mid-entry — on top of it.
        node.core.wal_writer.close()
        node.core.block_store.close()
        self.nodes[authority] = None
        if torn_tail_bytes > 0:
            # The tear lands where appends land: the active segment of a
            # segmented WAL, the file itself for a single-file log.
            from .storage import active_wal_file

            target = active_wal_file(self._wal_path(authority))
            size = os.path.getsize(target)
            with open(target, "r+b") as f:
                f.truncate(max(0, size - torn_tail_bytes))

    async def restart(self, authority: int) -> NetworkSyncer:
        assert authority in self.down, f"authority {authority} is not down"
        self.recorders[authority].record("restart")
        node = self._build_node(authority)  # WAL replay happens here
        self.nodes[authority] = node
        await node.start()
        self.down.discard(authority)
        await self.sim_net.restart(authority)
        return node

    # -- epoch reconfiguration (reconfig.py) --

    async def join(self, authority: int) -> NetworkSyncer:
        """First boot of an ``absent`` authority mid-run: a fresh epoch-0
        start from an empty WAL.  The joiner discovers the current
        committee by replaying the committed sequence — or, far behind,
        by adopting a snapshot manifest whose epoch chain carries every
        boundary it slept through."""
        assert authority in self.absent, f"authority {authority} not absent"
        self.recorders[authority].record("join")
        node = self._build_node(authority)
        self.nodes[authority] = node
        await node.start()
        self.down.discard(authority)
        self.absent.discard(authority)
        if self.health_monitor is not None:
            self.health_monitor.note_joined(authority)
        await self.sim_net.restart(authority)
        return node

    async def retire(self, authority: int) -> None:
        """Clean departure (a committed REMOVE change): stop the node and
        keep it gone.  Deliberately NOT a crash — no crash event is
        recorded, the health plane marks the authority retired (not down),
        and no restart ever follows."""
        node = self.nodes[authority]
        assert node is not None, f"authority {authority} is already down"
        self.retired.add(authority)
        self.down.add(authority)
        self.recorders[authority].record("retire")
        probe = self.probes.get(authority)
        if probe is not None:
            probe.detach()
        if self.health_monitor is not None:
            self.health_monitor.note_retired(authority)
        self.sim_net.crash(authority)
        await node.stop()
        node.core.wal_writer.close()
        node.core.block_store.close()
        self.nodes[authority] = None

    def submit_change(self, via: int, change) -> None:
        """Plant a committee-change transaction on ``via``'s block handler:
        it rides the next own proposal as an ordinary Share and takes
        effect when the committed sequence orders it."""
        node = self.nodes[via]
        assert node is not None, f"authority {via} is down"
        node.core.block_handler.inject(change.to_bytes())

    def inject(self, via: int, payload: bytes) -> None:
        """Plant an arbitrary transaction payload on ``via``'s block handler
        (the execution-plane workload rides the same next-own-proposal path
        as committee changes)."""
        node = self.nodes[via]
        assert node is not None, f"authority {via} is down"
        node.core.block_handler.inject(payload)

    async def stop(self) -> None:
        if self.health_monitor is not None:
            self.health_monitor.stop()
        for node in self.nodes:
            if node is None:
                continue
            await node.stop()
            node.core.wal_writer.close()
            node.core.block_store.close()
        self.sim_net.close()

    # -- commit accessors (all via the checker: restart-proof) --

    def committed_height(self, authority: int) -> int:
        return self.checker.committed_height(authority)

    def sequences(self) -> Dict[int, List[BlockReference]]:
        out: Dict[int, List[BlockReference]] = {}
        for a in range(self.n):
            try:
                out[a] = self.checker.sequence(a)
            except SafetyViolation:
                if a not in self.checker.adversaries:
                    raise
                # An adversary's own gap is attributed evidence (already in
                # adversary_divergence via check()), not a report failure.
                out[a] = []
        return out



# ---------------------------------------------------------------------------
# The engine


class ChaosEngine:
    """Executes a :class:`FaultPlan` against a :class:`ChaosSimHarness`.

    Acts as BOTH the timed-event scheduler (partitions, crash-restarts) and
    the :class:`SimulatedNetwork` fault injector (per-message drop /
    duplicate / delay draws from a plan-seeded RNG).  Every injected fault
    is appended to a log whose canonical-JSON serialization
    (:meth:`fault_log_bytes`) is byte-identical across same-seed runs —
    message order is deterministic under the DeterministicLoop, and the
    draws consume a dedicated ``Random`` in that order.
    """

    def __init__(self, harness: ChaosSimHarness, plan: FaultPlan) -> None:
        self.harness = harness
        self.plan = plan
        self.events = resolve_schedule(plan)
        self._link_rng = random.Random((plan.seed << 1) ^ 0x5EEDFA17)
        self._blocked: Set[Tuple[int, int]] = set()
        self._log: List[dict] = []
        self._task: Optional[asyncio.Task] = None
        # Byzantine layer (adversary.py): adversary nodes' outbound traffic
        # is rewritten BEFORE the benign link faults, on its own plan-seeded
        # RNG, so composing attacks with drops/partitions never shifts the
        # benign draw sequence of an adversary-free plan.
        self.adversary: Optional[AdversaryEngine] = (
            AdversaryEngine(
                plan.adversaries, harness.signers, harness.n, seed=plan.seed
            )
            if plan.adversaries
            else None
        )

    # -- lifecycle --

    def start(self) -> "ChaosEngine":
        self.harness.sim_net.fault_injector = self
        self._task = spawn_logged(self._run(), log, name="chaos-engine")
        return self

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        self.harness.sim_net.fault_injector = None

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        for event in self.events:
            delay = event["t"] - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            await self._execute(event)

    async def _execute(self, event: dict) -> None:
        kind = event["kind"]
        if kind == "partition_start":
            fault = PartitionFault.from_dict(event)
            self._blocked.update(fault.directed_pairs())
            self._record(kind, pairs=len(fault.directed_pairs()))
        elif kind == "partition_end":
            fault = PartitionFault.from_dict(event)
            self._blocked.difference_update(fault.directed_pairs())
            self._record(kind, pairs=len(fault.directed_pairs()))
        elif kind == "crash":
            node = event["node"]
            height = self.harness.committed_height(node)
            await self.harness.crash(
                node, torn_tail_bytes=event.get("torn_tail_bytes", 0)
            )
            self._count_fault(node, "crash")
            self._record(
                kind, node=node, committed_height=height,
                torn_tail_bytes=event.get("torn_tail_bytes", 0),
            )
        elif kind == "restart":
            node = event["node"]
            await self.harness.restart(node)
            self._count_fault(node, "restart")
            self._record(
                kind, node=node,
                committed_height=self.harness.committed_height(node),
            )

    # -- fault injector surface (SimulatedNetwork._pump) --

    def filter_batch(self, src: int, dst: int, batch: list) -> List[tuple]:
        if (src, dst) in self._blocked:
            self._count_fault(dst, "blackhole")
            self._record("blackhole", src=src, dst=dst, n=len(batch))
            return []
        t = asyncio.get_event_loop().time()
        groups = (
            self.adversary.transform(src, dst, batch, t)
            if self.adversary is not None
            else [(0.0, batch)]
        )
        out: List[tuple] = []
        for base_delay, messages in groups:
            for extra, sub in self._apply_link_faults(src, dst, messages, t):
                out.append((base_delay + extra, sub))
        return out

    def _apply_link_faults(
        self, src: int, dst: int, batch: list, t: float
    ) -> List[tuple]:
        rule = next(
            (f for f in self.plan.link_faults if f.matches(src, dst, t)), None
        )
        if rule is None:
            return [(0.0, batch)]
        rng = self._link_rng
        on_time: List = []
        extra_groups: List[tuple] = []
        dropped = duplicated = delayed = 0
        for message in batch:
            if rule.drop_p > 0.0 and rng.random() < rule.drop_p:
                dropped += 1
                continue
            if rule.delay_p > 0.0 and rng.random() < rule.delay_p:
                extra_groups.append(
                    (rng.uniform(*rule.delay_extra_s), [message])
                )
                delayed += 1
            else:
                on_time.append(message)
            if rule.duplicate_p > 0.0 and rng.random() < rule.duplicate_p:
                extra_groups.append(
                    (rng.uniform(*rule.delay_extra_s), [message])
                )
                duplicated += 1
        if dropped or duplicated or delayed:
            for kind, count in (
                ("drop", dropped), ("duplicate", duplicated), ("delay", delayed),
            ):
                if count:
                    self._count_fault(dst, kind, count)
            self._record(
                "link_faults", src=src, dst=dst,
                dropped=dropped, duplicated=duplicated, delayed=delayed,
            )
        return [(0.0, on_time)] + extra_groups

    # -- bookkeeping --

    def _record(self, kind: str, **fields) -> None:
        entry = {"t": asyncio.get_event_loop().time(), "kind": kind}
        entry.update(fields)
        self._log.append(entry)

    def _count_fault(self, node: int, kind: str, count: int = 1) -> None:
        metrics = self.harness.metrics[node]
        if metrics is not None:
            metrics.chaos_faults_total.labels(kind).inc(count)

    @property
    def fault_log(self) -> List[dict]:
        return list(self._log)

    def fault_log_bytes(self) -> bytes:
        return _canonical_json(self._log).encode()

    def fault_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for entry in self._log:
            if entry["kind"] == "link_faults":
                for key in ("dropped", "duplicated", "delayed"):
                    counts[key] = counts.get(key, 0) + entry[key]
            else:
                counts[entry["kind"]] = counts.get(entry["kind"], 0) + 1
        return counts


# ---------------------------------------------------------------------------
# One-call runner (tests + the `chaos` CLI subcommand)


@dataclass
class ChaosReport:
    """Everything a scenario needs to assert on (or a human to read)."""

    sequences: Dict[int, List[BlockReference]]
    fault_log: List[dict]
    fault_log_bytes: bytes
    schedule_bytes: bytes
    fault_counts: Dict[str, int]
    crash_events: List[dict]
    # Health plane (present when the scenario ran with an SLO config): the
    # deterministic fleet timeline, its canonical bytes, and every watchdog
    # alert — the run ships with its own diagnosis.
    health_timeline: List[dict] = field(default_factory=list)
    health_timeline_bytes: bytes = b""
    slo_alerts: List[dict] = field(default_factory=list)
    # Flight recorders: every node's canonical event-ring dump (byte-
    # identical across same-seed runs).  On a safety FAILURE the sim never
    # reaches this report — the rings land on disk instead
    # (``flight-recorder-<authority>.json`` next to the WALs).
    recorder_dumps: Dict[int, bytes] = field(default_factory=dict)
    # Byzantine layer (adversary.py): the injected attack schedule (ledger),
    # what the honest fleet detected (per-node counter census), and any
    # commit divergence attributed to a declared adversary.  All canonical
    # — byte-identical across same-seed runs.
    attack_log: List[dict] = field(default_factory=list)
    attack_log_bytes: bytes = b""
    attack_counts: Dict[str, int] = field(default_factory=dict)
    detections: Dict[int, dict] = field(default_factory=dict)
    adversary_divergence: List[dict] = field(default_factory=list)
    # Committed Share statements / blocks, observer -> block author ->
    # count (height-deduped): the scenario matrix's committed-throughput
    # numerators, per-author so honest-authored load is separable.  The
    # liveness gate uses BLOCKS (the protocol's own unit — Share counts
    # also reflect the test generator's batch-shaped minting).
    committed_tx: Dict[int, Dict[int, int]] = field(default_factory=dict)
    committed_blocks: Dict[int, Dict[int, int]] = field(default_factory=dict)
    # Epoch reconfiguration: the final epoch each authority reached, and
    # the audited boundary table (epoch -> [boundary_height, digest hex])
    # agreed by the honest fleet — empty when the scenario never
    # reconfigured.
    epochs: Dict[int, int] = field(default_factory=dict)
    epoch_boundaries: Dict[int, List] = field(default_factory=dict)
    # Execution plane: each authority's highest executed height and the root
    # it derived there, plus the honest fleet's agreed root chain
    # (height -> root hex; the per-height agreement itself is the
    # SafetyChecker's job — a state-root fork raises before this report is
    # built).  Empty when the scenario never ran the execution plane.
    executed: Dict[int, List] = field(default_factory=dict)
    state_root_chain: Dict[int, str] = field(default_factory=dict)

    @staticmethod
    def _from_authors(
        table: Dict[int, Dict[int, int]], authors: Set[int]
    ) -> Dict[int, int]:
        return {
            observer: sum(
                count
                for author, count in by_author.items()
                if author in authors
            )
            for observer, by_author in table.items()
        }

    def committed_tx_from(self, authors: Set[int]) -> Dict[int, int]:
        """observer -> committed Shares authored by ``authors``."""
        return self._from_authors(self.committed_tx, authors)

    def committed_blocks_from(self, authors: Set[int]) -> Dict[int, int]:
        """observer -> committed blocks authored by ``authors``."""
        return self._from_authors(self.committed_blocks, authors)

    def schedule_digest(self) -> str:
        return hashlib.sha256(self.fault_log_bytes).hexdigest()

    def attack_digest(self) -> str:
        return hashlib.sha256(self.attack_log_bytes).hexdigest()

    def detections_bytes(self) -> bytes:
        return _canonical_json(
            {str(a): d for a, d in sorted(self.detections.items())}
        ).encode()


def _labeled_counter_census(counter) -> Dict[str, float]:
    """Non-zero label->value census of a prometheus counter.  Only the
    ``_total`` samples enter (the ``_created`` companion carries a wall
    timestamp and would break same-seed byte-identity)."""
    out: Dict[str, float] = {}
    for family in counter.collect():
        for sample in family.samples:
            if not sample.name.endswith("_total") or not sample.value:
                continue
            key = ",".join(
                f"{k}={v}" for k, v in sorted(sample.labels.items())
            ) or "_"
            out[key] = sample.value
    return {k: out[k] for k in sorted(out)}


def collect_detections(harness: ChaosSimHarness) -> Dict[int, dict]:
    """Per-node detection census: what each (metrics-carrying) node's
    honest path caught and attributed.  The metrics objects survive
    crash-restarts, so the census spans each node's whole life."""
    detections: Dict[int, dict] = {}
    for authority in range(harness.n):
        metrics = harness.metrics[authority]
        if metrics is None:
            continue
        node: dict = {}
        for name, counter in (
            ("equivocation", metrics.mysticeti_equivocation_detected_total),
            ("invalid_blocks", metrics.mysticeti_invalid_blocks_total),
            ("malformed", metrics.mysticeti_malformed_frames_total),
        ):
            census = _labeled_counter_census(counter)
            if census:
                node[name] = census
        if node:
            detections[authority] = node
    return detections


def run_chaos_sim(
    plan: FaultPlan,
    n: int,
    duration_s: float,
    wal_dir: str,
    parameters: Optional[Parameters] = None,
    verifier_factory=None,
    with_metrics: bool = False,
    extra_fault=None,
    slo: Optional[SLOThresholds] = None,
    per_node_parameters: Optional[Dict[int, Parameters]] = None,
    latency_ranges=None,
    committee: Optional[Committee] = None,
    detsan=None,
    absent: Optional[Set[int]] = None,
) -> Tuple[ChaosReport, ChaosSimHarness]:
    """Run one chaos scenario to completion on a fresh DeterministicLoop.

    Returns the report plus the (stopped) harness so callers can inspect
    per-node metrics.  ``extra_fault(harness) -> awaitable`` is an optional
    test hook scheduled alongside the plan (e.g. killing an injected
    verifier backend mid-run).  ``detsan`` attaches a
    :class:`mysticeti_tpu.detsan.DetsanRecorder` to the loop so two runs
    of the same plan can be diffed event-by-event (tools/detsan.py).
    Raises :class:`SafetyViolation` if any committed prefix ever diverged.
    """
    from .runtime.simulated import run_simulation

    if plan.adversaries:
        if committee is None:
            # Byzantine scenarios verify REAL signatures end-to-end: the
            # default new_test committee shares one dummy key across all
            # authorities, which would reject every honest block.  The
            # benchmark committee's per-index keys match the harness
            # signers.
            committee = Committee.new_for_benchmarks(n)
        if verifier_factory is None:
            # An adversary plan with the AcceptAll default would make
            # `invalid_sig` a silent no-op (tampered blocks accepted and
            # committed, the detection counter never fires) — exactly what
            # a CLI `chaos --plan` replay of a Byzantine plan would hit.
            # Default to the sim re-sign oracle: exact Ed25519 semantics,
            # deterministic, sim-priced.
            from .scenarios import oracle_verifier_factory

            verifier_factory = oracle_verifier_factory(n)
    harness = ChaosSimHarness(
        n,
        wal_dir,
        parameters=parameters,
        committee=committee,
        verifier_factory=verifier_factory,
        with_metrics=with_metrics,
        slo=slo,
        per_node_parameters=per_node_parameters,
        latency_ranges=latency_ranges,
        adversaries={spec.node for spec in plan.adversaries},
        absent=absent,
    )
    engine = ChaosEngine(harness, plan)

    async def main() -> ChaosReport:
        await harness.start()
        engine.start()
        extra = (
            spawn_logged(extra_fault(harness), log, name="chaos-extra-fault")
            if extra_fault is not None
            else None
        )
        await asyncio.sleep(duration_s)
        engine.stop()
        if extra is not None:
            extra.cancel()
        await harness.stop()
        try:
            harness.checker.check()
        except SafetyViolation:
            # The flight recorder's reason to exist: the moment commit
            # safety fails, every LIVE node's event ring is dumped next to
            # the WALs (crashed nodes have no live ring to preserve — their
            # last dumpable state is whatever a restart rebuilt).
            for a in range(harness.n):
                if a in harness.down:
                    continue
                harness.recorders[a].dump(
                    "safety-failure",
                    path=os.path.join(wal_dir, f"flight-recorder-{a}.json"),
                )
            raise
        monitor = harness.health_monitor
        adversary = engine.adversary
        return ChaosReport(
            sequences=harness.sequences(),
            fault_log=engine.fault_log,
            fault_log_bytes=engine.fault_log_bytes(),
            schedule_bytes=schedule_bytes(plan),
            fault_counts=engine.fault_counts(),
            crash_events=[e for e in engine.fault_log if e["kind"] == "crash"],
            health_timeline=monitor.timeline if monitor else [],
            health_timeline_bytes=(
                monitor.timeline_bytes() if monitor else b""
            ),
            slo_alerts=monitor.alert_stream() if monitor else [],
            recorder_dumps={
                a: harness.recorders[a].snapshot_bytes()
                for a in range(harness.n)
            },
            attack_log=adversary.ledger.entries if adversary else [],
            attack_log_bytes=(
                adversary.ledger.ledger_bytes() if adversary else b""
            ),
            attack_counts=adversary.ledger.counts() if adversary else {},
            detections=collect_detections(harness),
            adversary_divergence=list(harness.checker.adversary_divergence),
            committed_tx={
                observer: dict(by_author)
                for observer, by_author in harness.checker.committed_tx.items()
            },
            committed_blocks={
                observer: dict(by_author)
                for observer, by_author in
                harness.checker.committed_blocks.items()
            },
            epochs={
                a: harness.checker.epoch_of(a)
                for a in range(harness.n)
                if harness.checker.epoch_of(a) > 0
            },
            epoch_boundaries={
                epoch: [height, digest.hex()]
                for table in harness.checker._epochs.values()
                for epoch, (height, digest) in table.items()
            },
            executed={
                a: [
                    harness.checker.executed_height(a),
                    (
                        harness.checker.state_root_at(
                            a, harness.checker.executed_height(a)
                        )
                        or b""
                    ).hex(),
                ]
                for a in range(harness.n)
                if harness.checker.executed_height(a) > 0
            },
            state_root_chain={
                height: root.hex()
                for a, table in sorted(harness.checker._state_roots.items())
                if a not in harness.checker.adversaries
                for height, root in table.items()
            },
        )

    return run_simulation(main(), seed=plan.seed, detsan=detsan), harness
