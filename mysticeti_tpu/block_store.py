"""Round-indexed block store over the WAL, with recovery replay.

Capability parity with ``mysticeti-core/src/block_store.rs``:

* index: round -> {(authority, digest) -> IndexEntry}, loaded/unloaded cache states
  (block_store.rs:28-47)
* ``BlockStore.open`` — WAL replay feeding a ``RecoveredStateBuilder`` (block_store.rs:50-116)
* DAG queries: ``get_blocks_by_round`` (:129), ``get_blocks_at_authority_round`` (:134),
  existence checks (:146-178), ancestry ``linked`` / ``linked_to_round`` (:284-327)
* dissemination cursors ``get_own_blocks`` / ``get_others_blocks`` (:220-240,434-476)
* cache eviction ``cleanup`` -> ``unload_below_round`` (:207-218,374-396)
* ``BlockWriter`` write-through (:38-41,504-518); ``OwnBlockData`` framing
  {next_entry, block} (:521-550); serializable ``CommitData`` (:552-573)
* WAL entry tags (:496-502)

Design notes: a single ``threading.RLock`` replaces the reference's parking_lot
RwLock — mutation comes only from the consensus owner task, readers may be the
metrics reporter or the dissemination tasks.  ``IndexEntry`` is a ``(position,
block-or-None)`` tuple rather than an enum; ``None`` means unloaded (read back
through the WAL mmap on demand).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .serde import Reader, Writer
from .tracing import logger
from .types import (
    AuthorityIndex,
    BlockReference,
    RoundNumber,
    Share,
    StatementBlock,
    TransactionLocator,
)
from .wal import HEADER_SIZE, POSITION_MAX, Tag, WalPosition, WalReader, WalWriter

log = logger(__name__)

WAL_ENTRY_BLOCK: Tag = 1
WAL_ENTRY_PAYLOAD: Tag = 2
WAL_ENTRY_OWN_BLOCK: Tag = 3
WAL_ENTRY_STATE: Tag = 4
# Commit entry carries both the linearizer's incremental state and the committed
# transaction-aggregator state (block_store.rs:500-502).
WAL_ENTRY_COMMIT: Tag = 5
# Snapshot catch-up adoption (storage.py): the node adopted a remote commit
# baseline mid-run; the persisted SnapshotManifest re-seeds the commit chain
# on the next recovery so the adopted prefix survives a crash.
WAL_ENTRY_SNAPSHOT: Tag = 6

_OWN_BLOCK_HEADER_SIZE = 8  # u64 next_entry (block_store.rs:526)

# IndexEntry: (wal position, loaded block or None)
IndexEntry = Tuple[WalPosition, Optional[StatementBlock]]


@dataclass
class OwnBlockData:
    """Own proposal + the WAL cursor past consumed pending entries (block_store.rs:521-550)."""

    next_entry: WalPosition
    block: StatementBlock

    def to_bytes(self) -> bytes:
        return self.next_entry.to_bytes(8, "little") + self.block.to_bytes()

    @staticmethod
    def from_bytes(data: bytes) -> "OwnBlockData":
        next_entry = int.from_bytes(data[:_OWN_BLOCK_HEADER_SIZE], "little")
        block = StatementBlock.from_bytes(data[_OWN_BLOCK_HEADER_SIZE:])
        return OwnBlockData(next_entry, block)

    def write_to_wal(self, writer: WalWriter) -> WalPosition:
        header = self.next_entry.to_bytes(8, "little")
        return writer.writev(WAL_ENTRY_OWN_BLOCK, (header, self.block.to_bytes()))


@dataclass
class CommitData:
    """Serializable CommittedSubDag: anchor + all block refs + height (block_store.rs:552-573)."""

    leader: BlockReference
    sub_dag: List[BlockReference]
    height: int

    def encode(self, w: Writer) -> None:
        self.leader.encode(w)
        w.u32(len(self.sub_dag))
        for ref in self.sub_dag:
            ref.encode(w)
        w.u64(self.height)

    @staticmethod
    def decode(r: Reader) -> "CommitData":
        leader = BlockReference.decode(r)
        sub_dag = [BlockReference.decode(r) for _ in range(r.u32())]
        return CommitData(leader, sub_dag, r.u64())


class BlockStore:
    """The DAG index.  Cheap to share (all methods take the internal lock)."""

    def __init__(
        self,
        authority: AuthorityIndex,
        num_authorities: int,
        wal_reader: WalReader,
        metrics=None,
    ) -> None:
        self._lock = threading.RLock()
        self._index: Dict[
            RoundNumber, Dict[Tuple[AuthorityIndex, bytes], IndexEntry]
        ] = {}
        self._own_blocks: Dict[RoundNumber, bytes] = {}
        self._highest_round: RoundNumber = 0
        self._authority = authority
        self._last_seen_by_authority: List[RoundNumber] = [0] * num_authorities
        self._last_own_block: Optional[BlockReference] = None
        self._wal_reader = wal_reader
        self._metrics = metrics
        # Equivocation detection (docs/adversary.md): per-authority count of
        # EXTRA digests observed live at an (authority, round) the index
        # already holds — the generalized form of the post-crash own-block
        # double-proposal handling below.  Detection fires on LIVE inserts
        # only (replay re-observes history already counted pre-crash) and
        # once per distinct conflicting digest (the index key existing means
        # this copy was already seen).  ``recorder`` (an optional
        # FlightRecorder) gets the event edge; the counter is
        # mysticeti_equivocation_detected_total{authority}.
        self.recorder = None
        self.equivocations_detected: Dict[AuthorityIndex, int] = {}

    # -- recovery (block_store.rs:50-116) --

    @classmethod
    def open(
        cls,
        authority: AuthorityIndex,
        wal_reader: WalReader,
        wal_writer: WalWriter,
        committee,
        metrics=None,
        checkpoint=None,
    ):
        """Replay the WAL, building the index and the recovered core/observer state.

        Returns ``(CoreRecoveredState, CommitObserverRecoveredState)``; the block
        store itself rides inside the core state (state.rs:72-94).

        With a ``checkpoint`` (storage.py), the index and recovery fold are
        seeded from it and replay starts at its recorded WAL position instead
        of byte zero — the O(recent) boot the lifecycle plane exists for.
        """
        from .state import RecoveredStateBuilder

        store = cls(authority, len(committee), wal_reader, metrics)
        builder = RecoveredStateBuilder()
        replay_start: WalPosition = 0
        if checkpoint is not None:
            builder.seed_checkpoint(checkpoint)
            replay_start = checkpoint.wal_position
            floor = (
                wal_writer.first_base()
                if hasattr(wal_writer, "first_base")
                else 0
            )
            dropped = 0
            dropped_max_round: RoundNumber = 0
            for reference, position, proposed in sorted(
                checkpoint.index, key=lambda entry: entry[1]
            ):
                if position < floor:
                    # The segment holding it was deleted by a GC pass AFTER
                    # this checkpoint was written (GC only guarantees the
                    # kept checkpoints' REPLAY positions, not their whole
                    # index).  The block is settled history.
                    dropped += 1
                    dropped_max_round = max(dropped_max_round, reference.round)
                    continue
                store._add_unloaded(reference, position, proposed=proposed)
                wal_writer.note_round(reference.round, position)
            if dropped:
                # Raise the recovered floor over the known-gone rounds so
                # nothing re-fetches or re-parks on them — they are exactly
                # the rounds the deleting GC pass retired.
                log.warning(
                    "%d checkpoint index entries below the retired WAL "
                    "floor dropped (rounds <= %d); recovered DAG floor "
                    "raised accordingly", dropped, dropped_max_round,
                )
                builder.note_retired_floor(dropped_max_round + 1)
        replayed_end: WalPosition = replay_start
        for pos, tag, payload in wal_reader.iter_from(
            replay_start, wal_writer.position()
        ):
            replayed_end = pos + HEADER_SIZE + len(payload)
            if tag == WAL_ENTRY_BLOCK:
                block = StatementBlock.from_bytes(payload)
                builder.block(pos, block)
            elif tag == WAL_ENTRY_PAYLOAD:
                builder.payload(pos, payload)
                continue
            elif tag == WAL_ENTRY_OWN_BLOCK:
                own = OwnBlockData.from_bytes(payload)
                builder.own_block(own)
                block = own.block
            elif tag == WAL_ENTRY_STATE:
                builder.state(payload)
                continue
            elif tag == WAL_ENTRY_COMMIT:
                r = Reader(payload)
                commits = [CommitData.decode(r) for _ in range(r.u32())]
                committed_state = r.bytes()
                r.expect_done()
                builder.commit_data(commits, committed_state)
                continue
            elif tag == WAL_ENTRY_SNAPSHOT:
                from .storage import SnapshotManifest

                builder.snapshot(SnapshotManifest.from_bytes(payload))
                continue
            else:
                raise ValueError(f"unknown wal tag {tag} at position {pos}")
            store._add_unloaded(
                block.reference, pos, proposed=tag == WAL_ENTRY_OWN_BLOCK
            )
            wal_writer.note_round(block.reference.round, pos)
        builder.note_replayed(max(0, replayed_end - replay_start))
        if replayed_end < wal_writer.position():
            # Torn tail (crash mid-write): replay stopped at the tear.  The
            # torn bytes must be truncated away before the first new append —
            # writing past them would leave an unreplayable gap that silently
            # loses every subsequent entry on the NEXT recovery.
            log.warning(
                "torn WAL tail: replay stopped at %d, discarding %d trailing "
                "bytes", replayed_end, wal_writer.position() - replayed_end,
            )
            wal_writer.truncate_to(replayed_end)
            wal_reader.cleanup()  # drop any mapping that covers the old size
        return builder.build(store)

    # -- writes --

    def insert_block(
        self, block: StatementBlock, position: WalPosition,
        proposed: bool = False,
    ) -> None:
        equivocated = False
        with self._lock:
            self._highest_round = max(self._highest_round, block.round())
            self._add_own_index(block.reference, proposed)
            self._update_last_seen(block.reference)
            entries = self._index.setdefault(block.round(), {})
            key = (block.author(), block.digest())
            if key not in entries and any(
                a == block.author() for (a, _) in entries
            ):
                # A SECOND distinct digest from this authority at this
                # round: equivocation, observed the moment the conflicting
                # copy lands in the DAG (valid signature and all — only
                # the index can see a double proposal).
                equivocated = True
                author = block.author()
                self.equivocations_detected[author] = (
                    self.equivocations_detected.get(author, 0) + 1
                )
            entries[key] = (position, block)
        if equivocated:
            log.warning(
                "equivocation detected: authority %d proposed a second "
                "block at round %d", block.author(), block.round(),
            )
            if self._metrics is not None:
                self._metrics.mysticeti_equivocation_detected_total.labels(
                    str(block.author())
                ).inc()
            if self.recorder is not None:
                self.recorder.record(
                    "equivocation-detected",
                    authority=block.author(),
                    round=block.round(),
                )

    def _add_unloaded(
        self, reference: BlockReference, position: WalPosition,
        proposed: bool = False,
    ) -> None:
        self._highest_round = max(self._highest_round, reference.round)
        self._add_own_index(reference, proposed)
        self._update_last_seen(reference)
        self._index.setdefault(reference.round, {})[
            (reference.authority, reference.digest)
        ] = (position, None)

    def _add_own_index(
        self, reference: BlockReference, proposed: bool = False
    ) -> None:
        """``proposed`` marks OUR proposal write path (``insert_own_block``
        and OWN_BLOCK replay) as opposed to a peer-delivered or fetched copy
        of an own-authority block."""
        if reference.authority != self._authority:
            return
        last = self._last_own_block.round if self._last_own_block else 0
        if reference.round > last:
            self._last_own_block = reference
        prev = self._own_blocks.get(reference.round)
        if prev is not None:
            if prev != reference.digest:
                # Post-crash equivocation: with fsync=false a torn WAL tail
                # can lose our own last proposal; after restart we re-propose
                # that round and may ALSO receive the lost block back from
                # peers (it sits in their causal histories).  The block we
                # actually PROPOSED must win the dissemination index — our
                # subsequent blocks build on it, and serving the stale copy
                # from get_own_blocks would push every post-restart proposal
                # through the slow missing-parent path.  Either way this is
                # a warning, never a raise: consensus tolerates the
                # equivocation like any other Byzantine double-proposal,
                # whereas crashing here would turn a recovered node into a
                # crash loop.
                if proposed:
                    self._own_blocks[reference.round] = reference.digest
                    if (
                        self._last_own_block is not None
                        and self._last_own_block.round == reference.round
                    ):
                        self._last_own_block = reference
                log.warning(
                    "own-block conflict at round %d (pre-crash proposal lost "
                    "to a torn WAL?); keeping the %s digest",
                    reference.round,
                    "re-proposed" if proposed else "first-indexed",
                )
            return
        self._own_blocks[reference.round] = reference.digest

    def _update_last_seen(self, reference: BlockReference) -> None:
        if reference.authority < len(self._last_seen_by_authority):
            if reference.round > self._last_seen_by_authority[reference.authority]:
                self._last_seen_by_authority[reference.authority] = reference.round

    # -- entry loading --

    def _load(self, entry: IndexEntry) -> StatementBlock:
        position, block = entry
        if block is not None:
            return block
        if self._metrics is not None:
            self._metrics.block_store_loaded_blocks.inc()
        tag, payload = self._wal_reader.read(position)
        if tag == WAL_ENTRY_BLOCK:
            return StatementBlock.from_bytes(payload)
        if tag == WAL_ENTRY_OWN_BLOCK:
            return OwnBlockData.from_bytes(payload).block
        raise ValueError(f"index entry at {position} has non-block tag {tag}")

    # -- queries --

    def get_block(self, reference: BlockReference) -> Optional[StatementBlock]:
        with self._lock:
            entry = self._index.get(reference.round, {}).get(
                (reference.authority, reference.digest)
            )
        return self._load(entry) if entry is not None else None

    def block_exists(self, reference: BlockReference) -> bool:
        with self._lock:
            return (reference.authority, reference.digest) in self._index.get(
                reference.round, {}
            )

    def get_blocks_by_round(self, round_: RoundNumber) -> List[StatementBlock]:
        with self._lock:
            entries = list(self._index.get(round_, {}).values())
        return [self._load(e) for e in entries]

    def get_blocks_at_authority_round(
        self, authority: AuthorityIndex, round_: RoundNumber
    ) -> List[StatementBlock]:
        with self._lock:
            entries = [
                e
                for (a, _), e in self._index.get(round_, {}).items()
                if a == authority
            ]
        return [self._load(e) for e in entries]

    def block_exists_at_authority_round(
        self, authority: AuthorityIndex, round_: RoundNumber
    ) -> bool:
        with self._lock:
            return any(a == authority for (a, _) in self._index.get(round_, {}))

    def all_blocks_exists_at_authority_round(
        self, authorities: Sequence[AuthorityIndex], round_: RoundNumber
    ) -> bool:
        with self._lock:
            present = {a for (a, _) in self._index.get(round_, {})}
        return all(a in present for a in authorities)

    def get_transaction(self, locator: TransactionLocator) -> Optional[bytes]:
        block = self.get_block(locator.block)
        if block is None or locator.offset >= len(block.statements):
            return None
        st = block.statements[locator.offset]
        return st.transaction if isinstance(st, Share) else None

    def len_expensive(self) -> int:
        with self._lock:
            return sum(len(m) for m in self._index.values())

    def highest_round(self) -> RoundNumber:
        with self._lock:
            return self._highest_round

    def last_seen_by_authority(self, authority: AuthorityIndex) -> RoundNumber:
        with self._lock:
            return self._last_seen_by_authority[authority]

    def last_own_block_ref(self) -> Optional[BlockReference]:
        with self._lock:
            return self._last_own_block

    @property
    def authority(self) -> AuthorityIndex:
        """The owning validator's index (immutable; set at open)."""
        return self._authority

    # -- dissemination cursors (block_store.rs:220-240,434-476) --

    def get_own_blocks(
        self, from_excluded: RoundNumber, limit: int
    ) -> List[StatementBlock]:
        with self._lock:
            rounds = sorted(r for r in self._own_blocks if r > from_excluded)[:limit]
            entries = [
                self._index[r][(self._authority, self._own_blocks[r])] for r in rounds
            ]
        return [self._load(e) for e in entries]

    def get_others_blocks(
        self, from_excluded: RoundNumber, authority: AuthorityIndex, limit: int
    ) -> List[StatementBlock]:
        with self._lock:
            entries: List[IndexEntry] = []
            for r in sorted(r for r in self._index if r > from_excluded):
                if len(entries) >= limit:
                    break
                for (a, _), e in self._index[r].items():
                    if a == authority:
                        entries.append(e)
            entries = entries[:limit]
        return [self._load(e) for e in entries]

    # -- ancestry (block_store.rs:284-327) --

    def linked(self, later: StatementBlock, earlier: StatementBlock) -> bool:
        """Is ``earlier`` an ancestor of ``later``?  Round-by-round frontier walk."""
        parents = [later]
        for r in range(later.round() - 1, earlier.round() - 1, -1):
            parent_refs = {inc for p in parents for inc in p.includes}
            parents = [
                b for b in self.get_blocks_by_round(r) if b.reference in parent_refs
            ]
        return earlier in parents

    def linked_to_round(
        self, later: StatementBlock, earlier_round: RoundNumber
    ) -> List[StatementBlock]:
        """All ancestors of ``later`` at ``earlier_round`` reachable via includes."""
        parents = [later]
        for r in range(later.round() - 1, earlier_round - 1, -1):
            parent_refs = {inc for p in parents for inc in p.includes}
            parents = [
                b for b in self.get_blocks_by_round(r) if b.reference in parent_refs
            ]
            if not parents:
                break
        return parents

    # -- storage lifecycle (storage.py) --

    def retire_below_round(self, gc_round: RoundNumber) -> int:
        """GC: drop every index entry with round strictly below ``gc_round``
        (the blocks' WAL segments are about to be deleted).  Unlike
        :meth:`cleanup` this is not an eviction — retired references are gone
        from this store; the linearizer/block-manager floors guarantee
        nothing asks for them again.  Returns entries removed."""
        removed = 0
        with self._lock:
            for round_ in [r for r in self._index if r < gc_round]:
                removed += len(self._index.pop(round_))
            for round_ in [r for r in self._own_blocks if r < gc_round]:
                del self._own_blocks[round_]
        if removed:
            log.debug(
                "retired %d index entries below round %d", removed, gc_round
            )
        return removed

    def index_entries_snapshot(
        self, from_round: RoundNumber = 0
    ) -> List[Tuple[BlockReference, WalPosition, bool]]:
        """Checkpoint payload: every (reference, wal position, is-own-
        proposal) at ``from_round`` or above, in WAL-position order (so a
        checkpoint-seeded index rebuilds with the same first-indexed
        semantics as replay)."""
        out: List[Tuple[BlockReference, WalPosition, bool]] = []
        with self._lock:
            for round_, entries in self._index.items():
                if round_ < from_round:
                    continue
                for (a, digest), (position, _block) in entries.items():
                    proposed = (
                        a == self._authority
                        and self._own_blocks.get(round_) == digest
                    )
                    out.append(
                        (BlockReference(a, round_, digest), position, proposed)
                    )
        out.sort(key=lambda entry: entry[1])
        return out

    # -- cache eviction (block_store.rs:207-218,374-396) --

    def cleanup(self, threshold_round: RoundNumber) -> int:
        if threshold_round == 0:
            return 0
        unloaded = 0
        with self._lock:
            for round_, m in self._index.items():
                if round_ > threshold_round:
                    continue
                for key, (pos, block) in m.items():
                    if block is not None:
                        m[key] = (pos, None)
                        unloaded += 1
        self._wal_reader.cleanup()
        if self._metrics is not None and unloaded:
            self._metrics.block_store_unloaded_blocks.inc(unloaded)
        return unloaded

    def close(self) -> None:
        """Release the WAL reader (mmap + fd).  Crash-restart simulation
        reopens the same path many times in one process; without this every
        restart would leak a descriptor and a mapping for the sim's whole
        lifetime."""
        self._wal_reader.close()


class BlockWriter:
    """Write-through of blocks to WAL + index (block_store.rs:504-518).

    The reference implements this as a trait on ``(&mut WalWriter, &BlockStore)``;
    here it is a tiny binding object constructed wherever both halves are in hand.
    """

    __slots__ = ("wal_writer", "block_store")

    def __init__(self, wal_writer: WalWriter, block_store: BlockStore) -> None:
        self.wal_writer = wal_writer
        self.block_store = block_store

    def insert_block(self, block: StatementBlock) -> WalPosition:
        pos = self.wal_writer.write(WAL_ENTRY_BLOCK, block.to_bytes())
        self.block_store.insert_block(block, pos)
        self.wal_writer.note_round(block.round(), pos)
        return pos

    def insert_own_block(self, data: OwnBlockData) -> WalPosition:
        pos = data.write_to_wal(self.wal_writer)
        self.block_store.insert_block(data.block, pos, proposed=True)
        self.wal_writer.note_round(data.block.round(), pos)
        return pos
