"""Canonical deterministic binary serialization for consensus objects.

The reference uses bincode for every wire/storage encoding (e.g. Data<T> caching in
``mysticeti-core/src/data.rs:22-44`` and the 4-byte length-prefixed frames in
``mysticeti-core/src/network.rs:397-459``).  This framework defines its own compact
little-endian format instead — bincode compatibility is not a goal; determinism and
zero-ambiguity are, because block digests and signatures are computed over these bytes.

Format primitives:
  u8 / u32 / u64  little-endian fixed width
  bytes           u32 length prefix + raw bytes
  list            u32 count prefix + items
All composite encoders write into a single ``bytearray`` to avoid intermediate copies.
"""
from __future__ import annotations

import struct

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


class Writer:
    """Append-only canonical encoder."""

    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    def u8(self, v: int) -> "Writer":
        self.buf += _U8.pack(v)
        return self

    def u32(self, v: int) -> "Writer":
        self.buf += _U32.pack(v)
        return self

    def u64(self, v: int) -> "Writer":
        self.buf += _U64.pack(v)
        return self

    def fixed(self, b: bytes) -> "Writer":
        """Raw bytes with no length prefix (fixed-size fields like digests/signatures)."""
        self.buf += b
        return self

    def bytes(self, b: bytes) -> "Writer":
        self.buf += _U32.pack(len(b))
        self.buf += b
        return self

    def finish(self) -> bytes:
        return bytes(self.buf)


class Reader:
    """Sequential canonical decoder with bounds checking.

    ``data`` may be ``bytes`` or a ``memoryview``.  With a memoryview input
    the variable-length :meth:`bytes` fields come back as sub-views over the
    caller's buffer — the zero-copy receive mode the mesh read path uses for
    block payloads (``network.decode_message``); the caller owns the buffer
    lifetime and must materialize (``bytes(view)``) anything that outlives
    it.  Fixed-width fields (:meth:`fixed`) always materialize: digests and
    signatures are used as dict keys and must stay hashable.
    """

    __slots__ = ("data", "pos")

    def __init__(self, data, pos: int = 0) -> None:
        self.data = data
        self.pos = pos

    def _take(self, n: int):
        end = self.pos + n
        if end > len(self.data):
            # Error text contract: the native batched parser
            # (mysticeti_native.cpp parse_blocks_spans) reproduces this
            # exact message — the data-plane parity corpus asserts torn
            # frames are indistinguishable across the native/fallback
            # paths, so any wording change here must land there too.
            raise SerdeError(
                f"truncated input: need {n} bytes at {self.pos}, have {len(self.data)}"
            )
        out = self.data[self.pos : end]
        self.pos = end
        return out

    def u8(self) -> int:
        return _U8.unpack(self._take(1))[0]

    def u32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self._take(8))[0]

    def fixed(self, n: int) -> bytes:
        return bytes(self._take(n))

    def bytes(self):
        """Length-prefixed field: a fresh ``bytes`` for bytes input, a
        zero-copy sub-view for memoryview input (see class docstring)."""
        n = self.u32()
        return self._take(n)

    def done(self) -> bool:
        return self.pos == len(self.data)

    def expect_done(self) -> None:
        if not self.done():
            # Same contract as _take: the native parser emits this message
            # verbatim for over-long Blocks payloads.
            raise SerdeError(f"trailing garbage: {len(self.data) - self.pos} bytes")


class SerdeError(ValueError):
    pass
