"""Node binary: genesis, run, dry-run, testbed subcommands.

Capability parity with ``mysticeti/src/main.rs``:

* ``benchmark-genesis`` (:36-43,116-156) — emit committee.yaml, parameters.yaml
  and per-authority private configs (key seed + storage dir).
* ``run`` (:44-58,159-185) — start one validator from config files.
* ``dry-run`` (:59-67,229-268) — single-command local validator: generates an
  in-process benchmark config for N authorities and runs one of them.
* ``testbed`` (:68-73,187-227) — N in-process validators on localhost.

Plus this framework's switch: ``--verifier {accept,cpu,tpu,tpu-only}``
selects the signature backend: ``tpu`` is the hybrid policy (batched JAX
kernel for large batches, CPU oracle for small ones — SURVEY §7 hard part
#2), ``tpu-only`` pins every batch to the kernel (saturation benchmarks),
``cpu`` is the serial OpenSSL oracle (reference behavior).
"""
from __future__ import annotations

import argparse
import asyncio
import os
import sys
from typing import List, Optional

import yaml

from .committee import Authority, Committee, STAKE_WEIGHTED
from .config import Identifier, Parameters, PrivateConfig
from .crypto import Signer
from .validator import Validator


VERIFIER_CHOICES = ["accept", "cpu", "tpu", "tpu-only", "cpu-agg", "tpu-agg"]


def _benchmark_parameters(ips: List[str]) -> Parameters:
    return Parameters.new_for_benchmarks(ips)


def benchmark_genesis(
    ips: List[str], working_dir: str, node_parameters: Optional[Parameters] = None
) -> None:
    """main.rs:116-156."""
    os.makedirs(working_dir, exist_ok=True)
    committee_size = len(ips)
    signers = Committee.benchmark_signers(committee_size)
    committee = Committee(
        [
            Authority(1, s.public_key, hostname=ip)
            for s, ip in zip(signers, ips)
        ],
        leader_election=STAKE_WEIGHTED,
    )
    committee.dump(os.path.join(working_dir, "committee.yaml"))
    parameters = node_parameters or _benchmark_parameters(ips)
    parameters.dump(os.path.join(working_dir, "parameters.yaml"))
    for i in range(committee_size):
        private_dir = os.path.join(working_dir, f"validator-{i}")
        private = PrivateConfig.new_in_dir(i, private_dir)
        with open(os.path.join(private_dir, "seed"), "wb") as f:
            f.write(i.to_bytes(32, "little"))


def _apply_storage_overrides(parameters: Parameters, args) -> None:
    """CLI storage-lifecycle + tracing flags override the parameters file
    (run) or the generated genesis (testbed): one knob block, one override
    path."""
    storage = parameters.storage
    if getattr(args, "gc_depth", None) is not None:
        storage.gc_depth = args.gc_depth
    if getattr(args, "segment_bytes", None) is not None:
        storage.segment_bytes = args.segment_bytes
    if getattr(args, "checkpoint_interval", None) is not None:
        storage.checkpoint_interval = args.checkpoint_interval
    if getattr(args, "snapshot_catchup", False):
        storage.snapshot_catchup = True
    if getattr(args, "timestamp_frames", False):
        parameters.synchronizer.timestamp_frames = True
    # Ingress-plane flags (one IngressParameters block, config.py).
    ingress = parameters.ingress
    if getattr(args, "no_ingress", False):
        ingress.enabled = False
    if getattr(args, "gateway_port_base", None) is not None:
        ingress.gateway_port_base = args.gateway_port_base
    if getattr(args, "mempool_max_transactions", None) is not None:
        ingress.mempool_max_transactions = args.mempool_max_transactions
    if getattr(args, "admission_initial", None) is not None:
        ingress.admission_initial_tx_s = float(args.admission_initial)
    if getattr(args, "no_admission", False):
        ingress.admission = False
    # Execution plane (execution.py): the deterministic account/transfer
    # state machine folding the committed sequence.
    if getattr(args, "execution", False):
        parameters.execution = True


async def run_node(
    authority: int,
    committee_path: str,
    parameters_path: str,
    private_dir: str,
    verifier: str = "cpu",
    tps: Optional[int] = None,
    storage_args=None,
) -> None:
    """main.rs:159-185."""
    from . import spans
    from .profiling import start_from_env, stop_from_env

    # MYSTICETI_PROFILE=<path>.folded: lifetime flamegraph, now fed through
    # the per-subsystem accountant (profiling.py); MYSTICETI_PERF_REPORT=
    # <path>.json additionally writes the node's attribution report
    # (per-subsystem CPU seconds, GIL convoy ratio) at shutdown — the input
    # tools/perf_attr.py aggregates into the PERF_ATTR artifact.
    start_from_env()
    # MYSTICETI_TRACE=<path>.json: per-block pipeline spans, exported as
    # Chrome trace-event JSON (Perfetto-loadable) at shutdown, with periodic
    # atomic flushes so a SIGKILL'd node still leaves a snapshot.
    spans.start_from_env()
    # MYSTICETI_CPROFILE=<path> (+ optional MYSTICETI_EXIT_AFTER=<s>): exact
    # deterministic profile of the node's event loop, dumped on clean exit —
    # the sampling profiler can't attribute C-extension time and benchmark
    # fleets SIGKILL their nodes, so a timed clean exit is the way to get a
    # trustworthy in-fleet profile.
    cprofile_path = os.environ.get("MYSTICETI_CPROFILE")
    profiler = None
    if cprofile_path:
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()
    exit_after = float(os.environ.get("MYSTICETI_EXIT_AFTER", "0") or 0)
    committee = Committee.load(committee_path)
    parameters = Parameters.load(parameters_path)
    if storage_args is not None:
        _apply_storage_overrides(parameters, storage_args)
    private = PrivateConfig.new_in_dir(authority, private_dir)
    seed_path = os.path.join(private_dir, "seed")
    with open(seed_path, "rb") as f:
        signer = Signer.from_seed(f.read())
    validator = await Validator.start_benchmarking(
        authority,
        committee,
        parameters,
        private,
        signer=signer,
        tps=tps,
        verifier=verifier,
    )
    # Orderly shutdown on SIGTERM (fleet runners/operators stopping a node):
    # flush the span-trace tail and the last metrics window through
    # Validator.stop instead of dying mid-flush — only SIGKILL loses tails.
    import signal as _signal

    term = asyncio.Event()
    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(_signal.SIGTERM, term.set)
    except (NotImplementedError, RuntimeError):  # non-unix / nested loop
        pass
    try:
        completion = asyncio.ensure_future(
            validator.network_syncer.await_completion()
        )
        term_wait = asyncio.ensure_future(term.wait())
        timeout = exit_after if exit_after > 0 else None
        done, pending = await asyncio.wait(
            (completion, term_wait),
            timeout=timeout,
            return_when=asyncio.FIRST_COMPLETED,
        )
        for task in pending:
            task.cancel()
        if completion in done:
            completion.result()  # a node that died with an error must raise
        else:
            # Timed exit or SIGTERM: clean WAL close + network shutdown +
            # telemetry tail flush.
            await validator.stop()
    finally:
        if profiler is not None:
            profiler.disable()
            profiler.dump_stats(
                cprofile_path.replace("%p", str(os.getpid()))
            )
        stop_from_env()
        spans.stop_from_env()


async def testbed(committee_size: int, working_dir: str, duration_s: float,
                  verifier: str = "cpu", storage_args=None) -> List:
    """N in-process validators on localhost (main.rs:187-227)."""
    from . import spans

    spans.start_from_env()  # one trace for the whole in-process fleet
    try:
        ips = ["127.0.0.1"] * committee_size
        benchmark_genesis(ips, working_dir)
        committee = Committee.load(os.path.join(working_dir, "committee.yaml"))
        parameters = Parameters.load(os.path.join(working_dir, "parameters.yaml"))
        if storage_args is not None:
            _apply_storage_overrides(parameters, storage_args)
        signers = Committee.benchmark_signers(committee_size)
        validators = []
        for i in range(committee_size):
            private = PrivateConfig.new_in_dir(
                i, os.path.join(working_dir, f"validator-{i}")
            )
            validators.append(
                await Validator.start_benchmarking(
                    i,
                    committee,
                    parameters,
                    private,
                    signer=signers[i],
                    serve_metrics_endpoint=False,
                    verifier=verifier,
                )
            )
        await asyncio.sleep(duration_s)
        committed = [v.committed_leaders() for v in validators]
        for v in validators:
            await v.stop()
    finally:
        spans.stop_from_env()
    return committed


def main(argv: Optional[List[str]] = None) -> int:
    from .tracing import setup_logging

    setup_logging()  # honors MYSTICETI_LOG (RUST_LOG-style env filter)
    parser = argparse.ArgumentParser(prog="mysticeti-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    g = sub.add_parser("benchmark-genesis", help="emit benchmark configs")
    g.add_argument("--ips", nargs="+", required=True)
    g.add_argument("--working-directory", default="genesis")

    def add_storage_flags(p):
        p.add_argument("--gc-depth", type=int, default=None,
                       help="rounds retained behind the last committed "
                       "leader before WAL segments are deleted (0 = never)")
        p.add_argument("--segment-bytes", type=int, default=None,
                       help="WAL segment roll threshold (<= 0 = legacy "
                       "single-file log: no checkpoints, no GC)")
        p.add_argument("--checkpoint-interval", type=int, default=None,
                       help="commits between durable checkpoints (0 = off)")
        p.add_argument("--snapshot-catchup", action="store_true",
                       help="arm the snapshot catch-up streams (wire tags "
                       "9/10/11): far-behind peers bootstrap from a commit "
                       "baseline + recent block window, not full history")
        p.add_argument("--timestamp-frames", action="store_true",
                       help="stamp block push frames with sender clocks "
                       "(wire tag 12): peers surface per-link transit and "
                       "the fleet-trace merger can align cross-node clocks "
                       "(docs/fleet-tracing.md)")
        # Ingress plane (docs/ingress.md).
        p.add_argument("--no-ingress", action="store_true",
                       help="disable the admission-controlled ingress plane "
                       "(restores the pre-r11 unbounded direct queue)")
        p.add_argument("--no-admission", action="store_true",
                       help="keep the bounded mempool but disable the AIMD "
                       "admission controller (pool caps still shed)")
        p.add_argument("--gateway-port-base", type=int, default=None,
                       help="serve the client RPC gateway on port "
                       "BASE+authority (wire tags 13-16; 0/unset = off)")
        p.add_argument("--mempool-max-transactions", type=int, default=None,
                       help="ingress mempool transaction cap (submissions "
                       "beyond it are SHED with a typed reject)")
        p.add_argument("--execution", action="store_true",
                       help="run the deterministic execution plane: fold "
                            "committed transactions through the "
                            "account/transfer state machine and serve the "
                            "EXECUTED notification suffix (docs/execution.md)")
        p.add_argument("--admission-initial", type=float, default=None,
                       help="initial AIMD-admitted rate ceiling, tx/s")

    r = sub.add_parser("run", help="run one validator")
    r.add_argument("--authority", type=int, required=True)
    r.add_argument("--committee-path", required=True)
    r.add_argument("--parameters-path", required=True)
    r.add_argument("--private-config-path", required=True)
    r.add_argument("--verifier", choices=VERIFIER_CHOICES, default="cpu")
    add_storage_flags(r)

    d = sub.add_parser("dry-run", help="one validator of an N-node local setup")
    d.add_argument("--committee-size", type=int, required=True)
    d.add_argument("--authority", type=int, required=True)
    d.add_argument("--working-directory", default="dryrun")
    d.add_argument("--verifier", choices=VERIFIER_CHOICES, default="cpu")
    add_storage_flags(d)

    t = sub.add_parser("testbed", help="N in-process validators")
    t.add_argument("--committee-size", type=int, required=True)
    t.add_argument("--working-directory", default="testbed")
    t.add_argument("--duration", type=float, default=30.0)
    t.add_argument("--verifier", choices=VERIFIER_CHOICES, default="cpu")
    add_storage_flags(t)

    o = sub.add_parser(
        "orchestrator",
        help="run a local benchmark sweep: boot a fleet, scrape, summarize, plot",
    )
    o.add_argument("--settings", help="settings.json path (overrides most flags)")
    o.add_argument("--nodes", type=int, default=4)
    o.add_argument("--loads", type=int, nargs="+", default=[100],
                   help="fixed offered loads (tx/s) to sweep")
    o.add_argument("--search", action="store_true",
                   help="binary-search the max sustainable load instead")
    o.add_argument("--starting-load", type=int, default=100)
    o.add_argument("--max-iterations", type=int, default=7,
                   help="search: probe budget (doubling + bisection runs)")
    o.add_argument("--duration", type=float, default=60.0)
    o.add_argument("--faults", type=int, default=0)
    o.add_argument("--fault-kind", choices=["none", "permanent", "crash-recovery"],
                   default="none")
    o.add_argument("--fault-interval", type=float, default=30.0)
    o.add_argument("--verifier", choices=VERIFIER_CHOICES, default="cpu")
    o.add_argument("--tps-per-node", type=int, default=None,
                   help="override the generator load split (default: load/nodes)")
    o.add_argument("--working-directory", default="benchmark-fleet")
    o.add_argument("--results-dir", default="benchmark-results")
    o.add_argument("--scrape-interval", type=float, default=10.0)
    o.add_argument("--plot", action="store_true", help="write latency-throughput plot")

    ch = sub.add_parser(
        "chaos",
        help="deterministic chaos sim: replay a FaultPlan from JSON over the "
        "virtual-time simulator (seeded network faults, timed partitions, "
        "crash-restarts with WAL replay) and audit commit safety",
    )
    ch.add_argument("--plan", required=True, help="FaultPlan JSON path")
    ch.add_argument("--nodes", type=int, default=10)
    ch.add_argument("--duration", type=float, default=30.0,
                    help="virtual seconds to simulate")
    ch.add_argument("--working-directory", default=None,
                    help="WAL directory (default: a fresh temp dir)")
    ch.add_argument("--dump-schedule", action="store_true",
                    help="print the resolved fault schedule and exit")
    ch.add_argument("--slo", default=None,
                    help="SLOThresholds JSON path (default: built-in chaos "
                    "thresholds); the run's health timeline + alerts ride "
                    "in the report")
    ch.add_argument("--health-out", default=None,
                    help="write the deterministic health timeline + SLO "
                    "alert stream as JSON")

    ov = sub.add_parser(
        "overload",
        help="deterministic overload sim: seeded N-node fleet under an "
        "offered-load multiplier ramp through the admission-controlled "
        "ingress plane; prints committed-vs-offered, the shed ledger, and "
        "the byte-stable shed-schedule digest (docs/ingress.md)",
    )
    ov.add_argument("--seed", type=int, default=0)
    ov.add_argument("--nodes", type=int, default=10)
    ov.add_argument("--duration", type=float, default=15.0,
                    help="virtual seconds to simulate")
    ov.add_argument("--base-tps", type=int, default=300,
                    help="per-node offered load at 1x")
    ov.add_argument("--schedule", default="0:3",
                    help="offered-load multiplier ramp, t:mult pairs "
                    "(e.g. '0:1,5:3,10:5')")
    ov.add_argument("--clients", type=int, default=3,
                    help="fairness lanes per node")
    ov.add_argument("--closed-loop", action="store_true",
                    help="clients consume SHED/retry-after verdicts")
    ov.add_argument("--report-out", default=None,
                    help="write the full report JSON here")

    sc = sub.add_parser(
        "scenarios",
        help="declarative resilience scenario matrix: run one named "
        "scenario (or the whole matrix) of composed Byzantine adversary "
        "mixes + benign chaos + storage churn + geo latency + version "
        "skew, each as an attacked run vs a same-seed clean twin "
        "(docs/adversary.md)",
    )
    sc.add_argument("--list", action="store_true",
                    help="list the matrix scenarios and exit")
    sc.add_argument("--scenario", default=None,
                    help="run only this named scenario (default: the whole "
                    "matrix)")
    sc.add_argument("--duration", type=float, default=None,
                    help="override the scenario's virtual duration")
    sc.add_argument("--seed", type=int, default=None,
                    help="override the scenario's seed")
    sc.add_argument("--working-directory", default=None,
                    help="WAL root (default: a fresh temp dir, removed)")
    sc.add_argument("--out", default=None,
                    help="write the matrix verdict document as JSON")

    vs = sub.add_parser(
        "verifier-service",
        help="shared per-host verifier service: one warmed JAX runtime "
        "serving every co-located validator over a unix socket "
        "(set MYSTICETI_VERIFIER_SOCKET on the nodes to use it)",
    )
    vs.add_argument("--socket", required=True, help="unix socket path")
    vs.add_argument("--committee-path", default=None,
                    help="prewarm for this committee while validators boot")
    vs.add_argument("--metrics-port", type=int, default=None,
                    help="expose /metrics + /healthz (queue depth, "
                    "in-flight per connection, dispatch sizes, padding)")

    f = sub.add_parser(
        "fleet",
        help="testbed lifecycle over a host pool: deploy/start/stop/destroy/"
        "status/install/update/logs",
    )
    f.add_argument("action", choices=[
        "deploy", "start", "stop", "destroy", "status", "install", "update",
        "logs",
    ])
    f.add_argument("--settings", help="settings.json with the host pool")
    f.add_argument("--hosts", nargs="*", default=None,
                   help="host pool override (user@addr ...)")
    f.add_argument("--count", type=int, default=None,
                   help="deploy: number of instances (default: whole pool)")
    f.add_argument("--region", default="local")
    f.add_argument("--state", default="testbed-state.json",
                   help="inventory state file")
    f.add_argument("--dest", default="downloaded-logs", help="logs: local dir")

    args = parser.parse_args(argv)

    if args.command == "benchmark-genesis":
        benchmark_genesis(args.ips, args.working_directory)
        print(f"genesis written to {args.working_directory}")
        return 0
    if args.command == "run":
        asyncio.run(
            run_node(
                args.authority,
                args.committee_path,
                args.parameters_path,
                args.private_config_path,
                verifier=args.verifier,
                storage_args=args,
            )
        )
        return 0
    if args.command == "dry-run":
        wd = args.working_directory
        ips = ["127.0.0.1"] * args.committee_size
        benchmark_genesis(ips, wd)
        asyncio.run(
            run_node(
                args.authority,
                os.path.join(wd, "committee.yaml"),
                os.path.join(wd, "parameters.yaml"),
                os.path.join(wd, f"validator-{args.authority}"),
                verifier=args.verifier,
                storage_args=args,
            )
        )
        return 0
    if args.command == "testbed":
        committed = asyncio.run(
            testbed(args.committee_size, args.working_directory, args.duration,
                    args.verifier, storage_args=args)
        )
        for i, seq in enumerate(committed):
            print(f"validator {i}: {len(seq)} committed leaders")
        return 0
    if args.command == "chaos":
        return run_chaos(args)
    if args.command == "overload":
        return run_overload(args)
    if args.command == "scenarios":
        return run_scenarios(args)
    if args.command == "verifier-service":
        from .verifier_service import run_service

        keys = None
        if args.committee_path:
            keys = Committee.load(args.committee_path).public_key_bytes()
        run_service(args.socket, keys, metrics_port=args.metrics_port)
        return 0
    if args.command == "orchestrator":
        return run_orchestrator(args)
    if args.command == "fleet":
        return run_fleet(args)
    return 1


def run_chaos(args) -> int:
    """The `chaos` subcommand: replay a FaultPlan from JSON on the
    deterministic simulator, print per-node commit progress, the injected
    fault tally, and the fault-schedule digest (byte-identical across runs
    of the same plan), and fail loudly on any commit-safety violation."""
    import json
    import tempfile

    from .chaos import (
        FaultPlan,
        SafetyViolation,
        resolve_schedule,
        run_chaos_sim,
    )

    with open(args.plan, "r", encoding="utf-8") as f:
        plan = FaultPlan.from_json(f.read())
    if args.dump_schedule:
        for event in resolve_schedule(plan):
            print(event)
        return 0
    from .health import SLOThresholds

    if args.slo:
        with open(args.slo, "r", encoding="utf-8") as f:
            slo = SLOThresholds.from_dict(json.load(f))
    else:
        slo = SLOThresholds(
            max_round_stall_s=8.0,
            max_commit_stall_s=10.0,
            max_authority_lag_rounds=15,
        )
    wal_dir = args.working_directory or tempfile.mkdtemp(prefix="chaos-")
    os.makedirs(wal_dir, exist_ok=True)
    try:
        report, _harness = run_chaos_sim(
            plan, args.nodes, args.duration, wal_dir, with_metrics=True,
            slo=slo,
        )
    except SafetyViolation as exc:
        print(f"SAFETY VIOLATION: {exc}")
        return 1
    for authority, sequence in sorted(report.sequences.items()):
        print(f"validator {authority}: {len(sequence)} committed leaders")
    faults = ", ".join(
        f"{kind}={count}" for kind, count in sorted(report.fault_counts.items())
    )
    print(f"faults injected: {faults or 'none'}")
    print(f"fault schedule digest: {report.schedule_digest()}")
    if plan.adversaries:
        attacks = ", ".join(
            f"{key}={count}"
            for key, count in sorted(report.attack_counts.items())
        )
        print(f"attacks injected: {attacks or 'none'}")
        print(f"attack ledger digest: {report.attack_digest()}")
        for authority, census in sorted(report.detections.items()):
            for surface, labels in sorted(census.items()):
                tally = ", ".join(
                    f"{label}={int(count)}"
                    for label, count in sorted(labels.items())
                )
                print(f"detected by A{authority} [{surface}]: {tally}")
    for alert in report.slo_alerts:
        who = "node" if alert["authority"] is None else f"A{alert['authority']}"
        print(
            f"SLO alert t={alert['t']:.1f}s {alert['kind']} [{alert['stage']}]"
            f" {who} (observed by A{alert['observer']}): {alert['detail']}"
        )
    print(
        f"health: {len(report.slo_alerts)} SLO alert(s) over "
        f"{len(report.health_timeline)} timeline sample(s)"
    )
    if args.health_out:
        with open(args.health_out, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "slo": slo.to_dict(),
                    "timeline": report.health_timeline,
                    "alerts": report.slo_alerts,
                },
                f, indent=1,
            )
            f.write("\n")
        print(f"health timeline written to {args.health_out}")
    print("safety: OK (identical committed prefixes on all nodes)")
    return 0


def run_scenarios(args) -> int:
    """The `scenarios` subcommand: the resilience matrix (scenarios.py).
    Each scenario prints its verdict line; the exit code is 0 only when
    every scenario run passed (safety + detection + throughput ratio)."""
    import dataclasses
    import json

    from .scenarios import default_matrix, run_matrix, scenario_by_name

    if args.list:
        for scenario in default_matrix():
            print(f"{scenario.name:<24} n={scenario.nodes:<3} "
                  f"{scenario.duration_s:>5.0f}s  {scenario.description}")
        return 0
    if args.scenario:
        selected = [scenario_by_name(args.scenario)]
    else:
        selected = default_matrix()
    overrides = {}
    if args.duration is not None:
        overrides["duration_s"] = args.duration
    if args.seed is not None:
        overrides["seed"] = args.seed
    if overrides:
        selected = [dataclasses.replace(s, **overrides) for s in selected]
    doc = run_matrix(selected, wal_root=args.working_directory)
    for verdict in doc["scenarios"]:
        name = verdict["scenario"]["name"]
        status = "PASS" if verdict["passed"] else "FAIL"
        detections = verdict.get("detections", {})
        print(
            f"{name:<24} {status}  ratio={verdict.get('throughput_ratio', 0.0):.2f} "
            f"committed={verdict.get('committed_tx', 0)} "
            f"attacks={sum(verdict.get('attack_counts', {}).values())} "
            f"detected={sum(1 for d in detections.values() if d['ok'])}"
            f"/{len(detections)}"
            + ("" if verdict["safety_ok"] else "  SAFETY-VIOLATION")
        )
    print(f"matrix: {doc['passed']} passed, {doc['failed']} failed")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"matrix verdicts written to {args.out}")
    return 0 if doc["all_pass"] else 1


def run_overload(args) -> int:
    """The `overload` subcommand: one seeded overload scenario on the
    deterministic simulator (docs/ingress.md).  Commit safety under
    overload is audited by the chaos SafetyChecker inside the runner."""
    import json

    from .ingress import OverloadScenario, run_overload_sim
    from .transactions_generator import parse_overload_schedule

    scenario = OverloadScenario(
        seed=args.seed,
        nodes=args.nodes,
        duration_s=args.duration,
        base_tps=args.base_tps,
        multiplier_schedule=parse_overload_schedule(args.schedule),
        clients_per_node=args.clients,
        closed_loop=args.closed_loop,
        max_per_proposal=30,
        mempool_max_transactions=600,
    )
    report = run_overload_sim(scenario)
    print(
        f"committed: {report.committed_tx} tx "
        f"({report.committed_tx_s:.1f} tx/s) of {report.offered_tx} offered "
        f"({report.admitted_tx} admitted)"
    )
    for reason, count in sorted(report.shed_by_reason.items()):
        print(f"shed[{reason}]: {count}")
    for lane, stats in sorted(report.lane_stats.items()):
        print(
            f"lane {lane}: drained={stats['drained']} shed={stats['shed']}"
            f" pending={stats['pending']}"
        )
    print(f"shed schedule digest: {report.shed_schedule_digest}")
    print("safety: OK (identical committed prefixes on all nodes)")
    if args.report_out:
        with open(args.report_out, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "scenario": scenario.to_dict(),
                    "committed_tx": report.committed_tx,
                    "committed_tx_s": report.committed_tx_s,
                    "offered_tx": report.offered_tx,
                    "admitted_tx": report.admitted_tx,
                    "shed_by_reason": report.shed_by_reason,
                    "shed_schedule_digest": report.shed_schedule_digest,
                    "lane_stats": report.lane_stats,
                    "commit_heights": report.commit_heights,
                    "generator_stats": report.generator_stats,
                },
                f, indent=1,
            )
            f.write("\n")
        print(f"report written to {args.report_out}")
    return 0


def run_fleet(args) -> int:
    """Testbed lifecycle CLI (orchestrator/src/main.rs testbed commands +
    testbed.rs:21-210): inventory over a static host pool, ssh-backed
    install/update/log-download."""
    from .orchestrator.settings import Settings
    from .orchestrator.ssh import SshManager
    from .orchestrator.testbed import StaticProvider, Testbed

    settings = Settings.load(args.settings) if args.settings else Settings()
    pool = args.hosts if args.hosts is not None else settings.hosts
    if settings.provider != "static":
        provider = settings.make_provider(state_path=args.state)
        if (
            settings.provider in ("rest", "aws")
            and args.action == "deploy"
            and not args.count
        ):
            raise SystemExit(
                f"{settings.provider} provider: `fleet deploy` requires --count"
            )
        # The ssh pool comes from the PROVIDER's live instances (a cloud
        # fleet has no static hosts list); resolved per-action below since
        # listing is async.
        ssh = None
    else:
        provider = StaticProvider(pool, state_path=args.state)
        ssh = SshManager(pool) if pool else None
    # settings.remote_repo's "." default addresses the ssh *runner* (commands
    # run from the checkout); as a clone target it would hit $HOME — keep
    # Testbed's own directory default unless the operator set a real path.
    remote_repo = (
        settings.remote_repo if settings.remote_repo not in ("", ".") else None
    )
    tb = Testbed(
        provider,
        ssh=ssh,
        repo_url=settings.repo_url,
        **({"remote_repo": remote_repo} if remote_repo else {}),
    )

    async def dispatch() -> None:
        if settings.provider in ("rest", "aws") and tb.ssh is None:
            hosts = [i.host for i in await provider.list_instances() if i.host]
            if hosts:
                tb.ssh = SshManager(hosts)
        if args.action == "deploy":
            await tb.deploy(args.count or len(pool), args.region)
        elif args.action == "start":
            await tb.start()
        elif args.action == "stop":
            await tb.stop()
        elif args.action == "destroy":
            await tb.destroy()
        elif args.action == "status":
            await tb.status()
        elif args.action == "install":
            await tb.install()
        elif args.action == "update":
            await tb.update()
        elif args.action == "logs":
            await tb.download_logs(settings.working_dir, args.dest)

    asyncio.run(dispatch())
    return 0


def run_orchestrator(args) -> int:
    """The orchestrator CLI (orchestrator/src/main.rs:36-195 equivalent):
    fixed-load sweep or max-load binary search over a local fleet, with
    summaries, log analysis, and an optional latency-throughput plot."""
    from .orchestrator.benchmark import LoadType, ParametersGenerator
    from .orchestrator.faults import FaultsType
    from .orchestrator.logs import analyze_logs
    from .orchestrator.orchestrator import Orchestrator
    from .orchestrator.plot import plot_latency_throughput
    from .orchestrator.settings import Settings

    if args.settings:
        settings = Settings.load(args.settings)
    else:
        settings = Settings(
            working_dir=args.working_directory,
            results_dir=args.results_dir,
            verifier=args.verifier,
        )
    if args.tps_per_node is not None:
        settings.tps_per_node = args.tps_per_node
    # Otherwise the per-run offered load flows through Runner.configure
    # (parameters.load // nodes) and any settings.json value stays the default.

    if args.fault_kind == "permanent":
        faults = FaultsType.permanent(args.faults)
    elif args.fault_kind == "crash-recovery":
        faults = FaultsType.crash_recovery(args.faults, args.fault_interval)
    else:
        faults = FaultsType.none()

    load_type = (
        LoadType.search(args.starting_load, max_iterations=args.max_iterations)
        if args.search
        else LoadType.fixed(list(args.loads))
    )
    generator = ParametersGenerator(
        args.nodes, load_type, duration_s=args.duration, faults=faults
    )
    runner = settings.make_runner()
    orchestrator = Orchestrator(
        runner,
        generator,
        results_dir=settings.results_dir,
        scrape_interval_s=args.scrape_interval,
    )
    collections = asyncio.run(orchestrator.run_benchmarks())
    for c in collections:
        print(c.display_summary())
    if args.search:
        print(f"max sustainable load: {generator.max_sustainable_load()} tx/s")
    analysis = analyze_logs(settings.working_dir)
    print(analysis.display())
    if args.plot:
        written = plot_latency_throughput(
            collections, os.path.join(settings.results_dir, "latency-throughput")
        )
        for path in written:
            print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
