"""Node binary: genesis, run, dry-run, testbed subcommands.

Capability parity with ``mysticeti/src/main.rs``:

* ``benchmark-genesis`` (:36-43,116-156) — emit committee.yaml, parameters.yaml
  and per-authority private configs (key seed + storage dir).
* ``run`` (:44-58,159-185) — start one validator from config files.
* ``dry-run`` (:59-67,229-268) — single-command local validator: generates an
  in-process benchmark config for N authorities and runs one of them.
* ``testbed`` (:68-73,187-227) — N in-process validators on localhost.

Plus this framework's switch: ``--verifier {accept,cpu,tpu}`` selects the
signature backend (TPU = the batched JAX kernel).
"""
from __future__ import annotations

import argparse
import asyncio
import os
import sys
from typing import List, Optional

import yaml

from .committee import Authority, Committee, STAKE_WEIGHTED
from .config import Identifier, Parameters, PrivateConfig
from .crypto import Signer
from .validator import Validator


def _benchmark_parameters(ips: List[str]) -> Parameters:
    return Parameters.new_for_benchmarks(ips)


def benchmark_genesis(
    ips: List[str], working_dir: str, node_parameters: Optional[Parameters] = None
) -> None:
    """main.rs:116-156."""
    os.makedirs(working_dir, exist_ok=True)
    committee_size = len(ips)
    signers = Committee.benchmark_signers(committee_size)
    committee = Committee(
        [
            Authority(1, s.public_key, hostname=ip)
            for s, ip in zip(signers, ips)
        ],
        leader_election=STAKE_WEIGHTED,
    )
    committee.dump(os.path.join(working_dir, "committee.yaml"))
    parameters = node_parameters or _benchmark_parameters(ips)
    parameters.dump(os.path.join(working_dir, "parameters.yaml"))
    for i in range(committee_size):
        private_dir = os.path.join(working_dir, f"validator-{i}")
        private = PrivateConfig.new_in_dir(i, private_dir)
        with open(os.path.join(private_dir, "seed"), "wb") as f:
            f.write(i.to_bytes(32, "little"))


async def run_node(
    authority: int,
    committee_path: str,
    parameters_path: str,
    private_dir: str,
    verifier: str = "cpu",
    tps: Optional[int] = None,
) -> None:
    """main.rs:159-185."""
    committee = Committee.load(committee_path)
    parameters = Parameters.load(parameters_path)
    private = PrivateConfig.new_in_dir(authority, private_dir)
    seed_path = os.path.join(private_dir, "seed")
    with open(seed_path, "rb") as f:
        signer = Signer.from_seed(f.read())
    validator = await Validator.start_benchmarking(
        authority,
        committee,
        parameters,
        private,
        signer=signer,
        tps=tps,
        verifier=verifier,
    )
    await validator.network_syncer.await_completion()


async def testbed(committee_size: int, working_dir: str, duration_s: float,
                  verifier: str = "cpu") -> List:
    """N in-process validators on localhost (main.rs:187-227)."""
    ips = ["127.0.0.1"] * committee_size
    benchmark_genesis(ips, working_dir)
    committee = Committee.load(os.path.join(working_dir, "committee.yaml"))
    parameters = Parameters.load(os.path.join(working_dir, "parameters.yaml"))
    signers = Committee.benchmark_signers(committee_size)
    validators = []
    for i in range(committee_size):
        private = PrivateConfig.new_in_dir(
            i, os.path.join(working_dir, f"validator-{i}")
        )
        validators.append(
            await Validator.start_benchmarking(
                i,
                committee,
                parameters,
                private,
                signer=signers[i],
                serve_metrics_endpoint=False,
                verifier=verifier,
            )
        )
    await asyncio.sleep(duration_s)
    committed = [v.committed_leaders() for v in validators]
    for v in validators:
        await v.stop()
    return committed


def main(argv: Optional[List[str]] = None) -> int:
    from .tracing import setup_logging

    setup_logging()  # honors MYSTICETI_LOG (RUST_LOG-style env filter)
    parser = argparse.ArgumentParser(prog="mysticeti-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    g = sub.add_parser("benchmark-genesis", help="emit benchmark configs")
    g.add_argument("--ips", nargs="+", required=True)
    g.add_argument("--working-directory", default="genesis")

    r = sub.add_parser("run", help="run one validator")
    r.add_argument("--authority", type=int, required=True)
    r.add_argument("--committee-path", required=True)
    r.add_argument("--parameters-path", required=True)
    r.add_argument("--private-config-path", required=True)
    r.add_argument("--verifier", choices=["accept", "cpu", "tpu"], default="cpu")

    d = sub.add_parser("dry-run", help="one validator of an N-node local setup")
    d.add_argument("--committee-size", type=int, required=True)
    d.add_argument("--authority", type=int, required=True)
    d.add_argument("--working-directory", default="dryrun")
    d.add_argument("--verifier", choices=["accept", "cpu", "tpu"], default="cpu")

    t = sub.add_parser("testbed", help="N in-process validators")
    t.add_argument("--committee-size", type=int, required=True)
    t.add_argument("--working-directory", default="testbed")
    t.add_argument("--duration", type=float, default=30.0)
    t.add_argument("--verifier", choices=["accept", "cpu", "tpu"], default="cpu")

    args = parser.parse_args(argv)

    if args.command == "benchmark-genesis":
        benchmark_genesis(args.ips, args.working_directory)
        print(f"genesis written to {args.working_directory}")
        return 0
    if args.command == "run":
        asyncio.run(
            run_node(
                args.authority,
                args.committee_path,
                args.parameters_path,
                args.private_config_path,
                verifier=args.verifier,
            )
        )
        return 0
    if args.command == "dry-run":
        wd = args.working_directory
        ips = ["127.0.0.1"] * args.committee_size
        benchmark_genesis(ips, wd)
        asyncio.run(
            run_node(
                args.authority,
                os.path.join(wd, "committee.yaml"),
                os.path.join(wd, "parameters.yaml"),
                os.path.join(wd, f"validator-{args.authority}"),
                verifier=args.verifier,
            )
        )
        return 0
    if args.command == "testbed":
        committed = asyncio.run(
            testbed(args.committee_size, args.working_directory, args.duration,
                    args.verifier)
        )
        for i, seq in enumerate(committed):
            print(f"validator {i}: {len(seq)} committed leaders")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
