"""The single-owner consensus state machine: ingest blocks, propose, commit, persist.

Capability parity with ``mysticeti-core/src/core.rs``:

* ``Core.open`` — genesis bootstrap or WAL recovery (core.rs:69-161)
* ``add_blocks`` — BlockManager gate, threshold clock, pending queue, handler run
  (core.rs:171-207)
* ``run_block_handler`` — handler statements become a persisted Payload pending
  entry (core.rs:209-225)
* ``try_new_block`` — drain pending up to the clock round, include-compression,
  sign, persist own block with the next-entry cursor, optional fsync
  (core.rs:227-328)
* ``try_commit`` -> UniversalCommitter + epoch-change trigger (core.rs:368-385)
* ``ready_new_block`` — leader-aware proposal gating (core.rs:401-450)
* ``handle_committed_subdag`` — epoch observation + state/commit WAL records
  (core.rs:452-490)
* ``cleanup`` (core.rs:387-395)

Single-writer discipline: exactly one owner task/thread may call the mutating
methods; everything else reads through the BlockStore (core_thread/spawned.rs).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Set, Tuple

from . import spans
from .block_manager import BlockManager
from .block_store import (
    BlockStore,
    BlockWriter,
    CommitData,
    OwnBlockData,
    WAL_ENTRY_COMMIT,
    WAL_ENTRY_PAYLOAD,
    WAL_ENTRY_STATE,
)
from .committee import Committee
from .config import Parameters
from .consensus import AuthorityRound, LeaderStatus
from .consensus.linearizer import CommittedSubDag
from .consensus.universal_committer import UniversalCommitter, UniversalCommitterBuilder
from .crypto import Signer
from .epoch_close import EpochManager
from .serde import Writer
from .state import CoreRecoveredState, Include, MetaStatement, Payload, encode_payload
from .threshold_clock import ThresholdClockAggregator
from .tracing import logger
from .types import (
    AuthorityIndex,
    AuthoritySet,
    BlockReference,
    RoundNumber,
    Share,
    StatementBlock,
)
from .wal import POSITION_MAX, WalPosition, WalSyncer, WalWriter

log = logger(__name__)


class CoreOptions:
    __slots__ = ("fsync",)

    def __init__(self, fsync: bool = False) -> None:
        self.fsync = fsync

    @classmethod
    def test(cls) -> "CoreOptions":
        return cls(fsync=False)

    @classmethod
    def production(cls) -> "CoreOptions":
        return cls(fsync=True)


class Core:
    def __init__(
        self,
        block_handler,
        authority: AuthorityIndex,
        committee: Committee,
        parameters: Parameters,
        recovered: CoreRecoveredState,
        wal_writer: WalWriter,
        options: Optional[CoreOptions] = None,
        signer: Optional[Signer] = None,
        metrics=None,
        storage=None,
    ) -> None:
        """Equivalent of ``Core::open`` (core.rs:69-161).

        ``storage`` is the node's :class:`~mysticeti_tpu.storage.
        StorageLifecycle` (checkpoint cadence, GC floor, snapshot baseline);
        ``None`` (bare test cores) keeps the seed behavior: cache eviction
        only, no checkpoints, unbounded log."""
        block_store: BlockStore = recovered.block_store
        pending = recovered.pending
        threshold_clock = ThresholdClockAggregator(0, metrics)
        writer = BlockWriter(wal_writer, block_store)

        # Commit-anchored reconfiguration (reconfig.py): the committee given
        # here is the epoch-0 genesis REGISTRY; a recovered epoch chain
        # (checkpoint/snapshot soft tail) re-derives the current epoch's
        # committee before anything below touches stake arithmetic.
        self.reconfig = None
        if parameters.reconfig:
            from .reconfig import EpochChain, ReconfigState

            committee.epoch_tolerant = True
            self.reconfig = ReconfigState(
                committee, EpochChain.from_bytes(recovered.epoch_chain)
            )
            committee = self.reconfig.committee

        # Deterministic execution plane (execution.py): account/transfer
        # state machine folded over the committed sequence.  A recovered
        # state (checkpoint/snapshot soft tail) restores the exact root the
        # node crashed out of; replayed commits below it are skipped by the
        # fold's height guard.
        self.execution = None
        if parameters.execution:
            from .execution import ExecutionState

            self.execution = ExecutionState(metrics=metrics)
            self.execution.recover(recovered.exec_state)

        if recovered.last_own_block is not None:
            # Recovery: replay pending includes into the clock (core.rs:89-95).
            for _, meta in pending:
                if isinstance(meta, Include):
                    threshold_clock.add_block(meta.reference, committee)
            last_own_block = recovered.last_own_block
            if metrics is not None:
                # WAL-recovered boot (vs genesis bootstrap): the chaos tier
                # asserts crash-restart actually drove this path.
                metrics.crash_recovery_total.inc()
        else:
            assert not pending
            own_genesis, other_genesis = committee.genesis_blocks(authority)
            assert own_genesis.author() == authority
            for block in other_genesis:
                threshold_clock.add_block(block.reference, committee)
                position = writer.insert_block(block)
                pending.append((position, Include(block.reference)))
            threshold_clock.add_block(own_genesis.reference, committee)
            last_own_block = OwnBlockData(next_entry=POSITION_MAX, block=own_genesis)
            writer.insert_own_block(last_own_block)

        if recovered.state is not None:
            block_handler.recover_state(
                recovered.state, watermark_round=block_store.highest_round()
            )

        self.block_manager = BlockManager(block_store, len(committee), metrics)
        # A checkpoint/snapshot-recovered store lacks everything below its
        # baseline floor; the manager must never park on those references.
        self.block_manager.gc_floor = recovered.gc_round
        self.pending: Deque[Tuple[WalPosition, MetaStatement]] = pending
        self.last_own_block: OwnBlockData = last_own_block
        self.block_handler = block_handler
        self.authority = authority
        self.threshold_clock = threshold_clock
        self.committee = committee
        last = recovered.last_committed_leader
        self.last_decided_leader = (
            AuthorityRound(last.authority, last.round) if last else AuthorityRound(0, 0)
        )
        self.wal_writer = wal_writer
        self.block_store = block_store
        self.metrics = metrics
        self.options = options or CoreOptions.test()
        self.signer = signer
        self.epoch_manager = EpochManager()
        self.rounds_in_epoch = parameters.rounds_in_epoch
        self.store_retain_rounds = parameters.store_retain_rounds
        self.leader_liveness_horizon = parameters.leader_liveness_horizon_rounds
        # Authorities the sync layer scored content-silent (live connection,
        # own blocks only ever recovered via relays/fetch — the withholder
        # shape).  Maintained by NetworkSyncer._score_missing; membership
        # checks only, so plain-set mutation from the net loop is safe.
        self.content_silent: Set[AuthorityIndex] = set()
        # leader -> last leader_round whose liveness skip was counted (the
        # metric counts skipped SLOTS, not readiness polls).
        self._leader_skip_marked: Dict[AuthorityIndex, RoundNumber] = {}
        self.storage = storage
        self.parameters = parameters
        # Called on every epoch switch with (new_committee, records): the
        # sync layer re-derives peer/relay/verifier state, the chaos checker
        # audits cross-node boundary agreement.  Registered post-construction
        # by the node assembly; fired on the consensus owner only.
        self.epoch_listeners: List = []
        # Called per folded commit with the ExecutionResult: the ingress
        # plane closes execute-phase finality and pushes gateway EXECUTED
        # notifications, the chaos checker audits cross-node root agreement.
        # Registered post-construction; fired on the consensus owner only.
        self.execution_listeners: List = []
        # Historical-committee memo for committee_for_epoch (catch-up
        # validates every pre-boundary block against its own epoch).
        self._epoch_committees: Dict[int, Committee] = {}
        self.committer: UniversalCommitter = self._build_committer()

        if self.reconfig is not None or self.execution is not None:
            # Crash landing between a boundary commit's WAL entry and the
            # next checkpoint: the replayed commits (everything after the
            # checkpoint baseline) are re-scanned so the node re-derives the
            # exact epoch — and the exact execution root — it crashed out
            # of.
            for commit in recovered.recovered_commits:
                blocks = [
                    b
                    for b in (
                        block_store.get_block(ref) for ref in commit.sub_dag
                    )
                    if b is not None
                ]
                if self.reconfig is not None:
                    transition = self.reconfig.observe_commit(
                        commit.height, commit.leader.round, blocks
                    )
                    if transition is not None:
                        self._switch_epoch(transition)
                if self.execution is not None:
                    self.execution.observe_commit(commit.height, blocks)
        if self.reconfig is not None:
            if metrics is not None:
                metrics.mysticeti_epoch.set(self.committee.epoch)
                metrics.mysticeti_committee_digest_info.labels(
                    self.reconfig.digest().hex()[:16]
                ).set(self.committee.epoch)

        if recovered.unprocessed_blocks:
            # Blocks after the last state snapshot re-run through the handler
            # (core.rs:152-158).
            self.run_block_handler(recovered.unprocessed_blocks)

    def _build_committer(self) -> UniversalCommitter:
        return (
            UniversalCommitterBuilder(self.committee, self.block_store, self.metrics)
            .with_wave_length(self.parameters.wave_length)
            .with_number_of_leaders(self.parameters.number_of_leaders)
            .with_pipeline(self.parameters.enable_pipelining)
            .build()
        )

    def _switch_epoch(self, transition) -> None:
        """Apply an epoch transition on the consensus owner: swap the
        committee every stake/quorum computation reads, rebuild the commit
        rule over it, and notify the sync/health/verifier listeners.  Called
        at a deterministic committed-sequence point (observe_commit), so
        every honest node performs the identical switch."""
        self.committee = transition.committee
        self.committer = self._build_committer()
        if hasattr(self.block_handler, "committee"):
            self.block_handler.committee = self.committee
        for record in transition.records:
            log.info(
                "epoch %d: boundary height=%d round=%d digest=%s stakes=%s",
                record.epoch, record.boundary_height, record.boundary_round,
                record.digest.hex()[:16], list(record.stakes),
            )
        if self.metrics is not None:
            self.metrics.mysticeti_epoch.set(self.committee.epoch)
            self.metrics.mysticeti_epoch_transitions_total.inc(
                len(transition.records)
            )
            self.metrics.mysticeti_committee_digest_info.labels(
                transition.records[-1].digest.hex()[:16]
            ).set(self.committee.epoch)
        for listener in self.epoch_listeners:
            listener(self.committee, transition.records)

    def committee_for_epoch(self, epoch: int) -> Committee:
        """Structural-validation committee for a block stamped ``epoch``.

        A historical block's threshold clock must be judged by ITS epoch's
        stake arithmetic — catch-up replays pre-boundary rounds long after
        the switch, and those include sets were built against the old
        quorum.  Epochs this node has not derived (including claimed
        future ones) fall back to the CURRENT committee: an author cannot
        buy lenient validation by stamping an epoch nobody has reached."""
        if self.reconfig is None or epoch == self.committee.epoch:
            return self.committee
        cached = self._epoch_committees.get(epoch)
        if cached is None:
            cached = self.reconfig.committee_for_epoch(epoch)
            if cached is None:
                return self.committee
            self._epoch_committees[epoch] = cached
        return cached

    # -- ingestion (core.rs:171-207) --

    def add_blocks(self, blocks: Sequence[StatementBlock]) -> List[BlockReference]:
        """Returns first-seen missing references needed to process the input."""
        writer = BlockWriter(self.wal_writer, self.block_store)
        processed, missing_references = self.block_manager.add_blocks(blocks, writer)
        tracer = spans.active()
        t_added = tracer.now() if tracer is not None else 0.0
        result = []
        for position, block in sorted(processed, key=lambda pb: pb[1].round()):
            self.threshold_clock.add_block(block.reference, self.committee)
            self.pending.append((position, Include(block.reference)))
            result.append(block)
            if tracer is not None:
                tracer.end_span(
                    "dag_add", block.reference,
                    authority=self.authority, t=t_added,
                )
                # Closed by the commit observer when the block is sequenced.
                tracer.begin_span(
                    "proposal_wait", block.reference,
                    authority=self.authority, t=t_added,
                )
        self.run_block_handler(result)
        return list(missing_references)

    def run_block_handler(self, processed: Sequence[StatementBlock]) -> None:
        statements = self.block_handler.handle_blocks(
            processed, require_response=not self.epoch_changing()
        )
        position = self.wal_writer.write(WAL_ENTRY_PAYLOAD, encode_payload(statements))
        self.pending.append((position, Payload(tuple(statements))))

    # -- proposal (core.rs:227-328) --

    def try_new_block(self) -> Optional[StatementBlock]:
        clock_round = self.threshold_clock.get_round()
        if clock_round <= self.last_proposed():
            return None

        # Take pending entries up to (not including) the first include at or past
        # the clock round (core.rs:240-251).
        first_include_index = len(self.pending)
        for i, (_, meta) in enumerate(self.pending):
            if isinstance(meta, Include) and meta.reference.round >= clock_round:
                first_include_index = i
                break
        taken = [self.pending.popleft() for _ in range(first_include_index)]

        # Include-compression: skip references already transitively covered by
        # the includes taken into this block (core.rs:253-278).
        references_in_block: Set[BlockReference] = set()
        references_in_block.update(self.last_own_block.block.includes)
        for _, meta in taken:
            if isinstance(meta, Include):
                block = self.block_store.get_block(meta.reference)
                if block is not None:
                    references_in_block.update(block.includes)

        includes: List[BlockReference] = [self.last_own_block.block.reference]
        statements: List = []
        for _, meta in taken:
            if isinstance(meta, Include):
                if meta.reference not in references_in_block:
                    includes.append(meta.reference)
            else:
                if not self.epoch_changing():
                    statements.extend(meta.statements)
        # Group shares into ONE contiguous run (relative order preserved on
        # both sides).  Every share RUN costs every observer a VoteRange
        # statement in its next block (committee.shared_ranges): when handler
        # calls interleave shares with votes across payload entries, the runs
        # fragment and per-block vote statements blow up to O(committee²) in
        # vote-heavy workloads — measured 360 VoteRanges/block at 20
        # authorities vs 19 with grouping.  Offsets inside the proposal are
        # assigned after this reordering, so locators stay self-consistent.
        if statements:
            shares = [s for s in statements if isinstance(s, Share)]
            if shares:
                statements = shares + [
                    s for s in statements if not isinstance(s, Share)
                ]

        assert includes
        from .runtime import timestamp_utc

        t_propose = spans.SpanTracer.now()
        block = StatementBlock.build(
            self.authority,
            clock_round,
            includes,
            statements,
            meta_creation_time_ns=int(timestamp_utc() * 1e9),
            epoch_marker=1 if self.epoch_changing() else 0,
            epoch=self.committee.epoch,
            signer=self.signer,
        )
        assert block.includes[0].authority == self.authority

        if self.metrics is not None:
            # Proposal-shape channels (metrics.rs:64-66): size from the
            # cached canonical bytes (computed by build), tx = Share runs,
            # votes = Vote/VoteRange statements.
            shares = sum(1 for s in statements if isinstance(s, Share))
            self.metrics.proposed_block_size_bytes.observe(
                len(block.to_bytes())
            )
            self.metrics.proposed_block_transaction_count.observe(shares)
            self.metrics.proposed_block_vote_count.observe(
                len(statements) - shares
            )
        tracer = spans.active()
        if tracer is not None:
            # The journey's t=0 (tools/fleet_trace.py): the author built and
            # signed the block here — every peer's transit/receive measures
            # from this edge once traces are merged.
            tracer.record_span(
                "propose", block.reference, t_propose,
                authority=self.authority,
            )
            # Own blocks skip receive/verify/dag_add; their pipeline starts
            # at the wait for commit.
            tracer.begin_span(
                "proposal_wait", block.reference, authority=self.authority
            )
        self.threshold_clock.add_block(block.reference, self.committee)
        self.block_handler.handle_proposal(block)
        next_entry = self.pending[0][0] if self.pending else POSITION_MAX
        self.last_own_block = OwnBlockData(next_entry=next_entry, block=block)
        BlockWriter(self.wal_writer, self.block_store).insert_own_block(
            self.last_own_block
        )
        if self.options.fsync:
            self.wal_writer.sync()
        # pending() is constantly False under the sim (walf() forces
        # synchronous writes), so this durability drain cannot skew a
        # seeded run — the PR 11 wal_backlog lesson, inverted.
        elif self.wal_writer.pending():  # lint: ignore[sim-taint]
            # Durability floor for OWN proposals (ADVICE r5): the async
            # append queue parks acknowledged entries in process memory, so
            # without this drain a plain process crash (OOM/SIGKILL) after
            # broadcast could lose the proposal and let the restarted node
            # equivocate at the same round.  flush() lands the bytes in the
            # page cache (the reference's synchronous-writev posture) BEFORE
            # the caller signals new_block_ready to the dissemination
            # streams; only OS/power failure retains a loss window, same as
            # the reference.  No fsync: that stays the syncer thread's job.
            # Cost: this blocks the owner until the drain thread lands the
            # queue — the pending() gate makes it free when already caught
            # up, and under backlog it repays, once per round, the same
            # bytes synchronous mode would have paid inline per append.
            self.wal_writer.flush()
        log.debug(
            "proposed block round=%d includes=%d statements=%d",
            block.round(),
            len(block.includes),
            len(block.statements),
        )
        return block

    # -- commit (core.rs:368-385) --

    def try_commit(self) -> List[StatementBlock]:
        sequence = self.committer.try_commit(self.last_decided_leader)
        if self.reconfig is not None and sequence:
            # Slot-sequential commit under reconfiguration: cap each batch at
            # the FIRST committed leader.  A change transaction anywhere in
            # that commit's sub-dag switches the committee, and every later
            # slot must be decided under the post-switch stake arithmetic —
            # a node that decided a whole multi-leader batch with the old
            # committee while a slower peer split it across the boundary
            # would diverge.  The syncer loops until a pass decides nothing,
            # so throughput is unchanged.
            for i, status in enumerate(sequence):
                if status.kind == LeaderStatus.COMMIT:
                    sequence = sequence[: i + 1]
                    break
        if sequence:
            self.last_decided_leader = sequence[-1].into_decided_author_round()
        if self.last_decided_leader.round > self.rounds_in_epoch:
            self.epoch_manager.epoch_change_begun()
        return [s.block for s in sequence if s.kind == LeaderStatus.COMMIT]

    def ready_new_block(self, period: int, connected_authorities: AuthoritySet) -> bool:
        """Leader-aware proposal gating (core.rs:401-450): propose when the previous
        round's (connected) leaders have been received, or there are none."""
        quorum_round = self.threshold_clock.get_round()
        if quorum_round <= max(self.last_decided_leader.round, period - 1):
            return False
        leader_round = quorum_round - 1
        leaders = self.committer.get_leaders(leader_round)
        if not leaders:
            return True
        connected_leaders = [
            l for l in leaders if connected_authorities.contains(l)
        ]
        if self.leader_liveness_horizon > 0:
            # Leader liveness scoring (docs/adversary.md): a leader whose
            # blocks have not been ACCEPTED locally for more than the
            # horizon is not worth gating the proposal on — a Byzantine
            # authority that signs invalidly (or withholds from us) would
            # otherwise tax every one of its slots with a full leader
            # timeout.  The timeout task stays as the universal backstop,
            # and the commit rule is untouched: the slot is still decided
            # (skip) by 2f+1 non-links, exactly as on a timeout.  An
            # authority that resumes producing acceptable blocks re-enters
            # the wait set as soon as its last-seen round catches back up.
            live = []
            for leader in connected_leaders:
                seen = self.block_store.last_seen_by_authority(leader)
                lagging = leader_round - seen > self.leader_liveness_horizon
                if lagging or leader in self.content_silent:
                    # Once per (leader, round): readiness is polled on
                    # every dispatcher event, so a bare inc() here would
                    # count polls (thousands per skipped slot), not skips.
                    if (
                        self.metrics is not None
                        and self._leader_skip_marked.get(leader) != leader_round
                    ):
                        self._leader_skip_marked[leader] = leader_round
                        self.metrics.mysticeti_leader_wait_skipped_total.labels(
                            str(leader)
                        ).inc()
                else:
                    live.append(leader)
            connected_leaders = live
        if not connected_leaders:
            return True
        return self.block_store.all_blocks_exists_at_authority_round(
            connected_leaders, leader_round
        )

    # -- commit persistence (core.rs:452-490) --

    def handle_committed_subdag(
        self, committed: List[CommittedSubDag], state: bytes
    ) -> List[CommitData]:
        commit_data = []
        for commit in committed:
            for block in commit.blocks:
                self.epoch_manager.observe_committed_block(block, self.committee)
            commit_data.append(
                CommitData(
                    leader=commit.anchor,
                    sub_dag=[b.reference for b in commit.blocks],
                    height=commit.height,
                )
            )
            if self.reconfig is not None:
                # Scan this commit's sub-dag (in linearized order) for
                # finalized committee changes; the switch happens HERE —
                # before the checkpoint below embeds the chain, and before
                # any later slot is decided (try_commit is slot-sequential
                # under reconfig, so `committed` holds at most one commit).
                transition = self.reconfig.observe_commit(
                    commit.height, commit.anchor.round, commit.blocks
                )
                if transition is not None:
                    self._switch_epoch(transition)
            if self.execution is not None:
                # Fold the sub-dag into the account state machine and
                # advance the root chain BEFORE the checkpoint below embeds
                # the state — a checkpoint must never be ahead of or behind
                # the commits it is anchored to.
                result = self.execution.observe_commit(
                    commit.height, commit.blocks
                )
                if result is not None:
                    for listener in self.execution_listeners:
                        listener(result)
        self.write_state()
        self.write_commits(commit_data, state)
        if self.storage is not None and commit_data:
            self.storage.note_commits(commit_data)
            if self.storage.should_checkpoint():
                self.storage.write_checkpoint(self, state)
        return commit_data

    def write_state(self) -> None:
        self.wal_writer.write(WAL_ENTRY_STATE, self.block_handler.state())

    def write_commits(self, commits: List[CommitData], state: bytes) -> None:
        w = Writer()
        w.u32(len(commits))
        for c in commits:
            c.encode(w)
        w.bytes(state)
        self.wal_writer.write(WAL_ENTRY_COMMIT, w.finish())

    # -- snapshot catch-up (storage.py; driven by the syncer) --

    def apply_snapshot(self, manifest) -> bool:
        """Adopt a remote commit baseline: persist the manifest (crash-safe
        re-adoption on replay), jump the decided-leader cursor, raise the
        block manager's floor, and release any parked blocks the new floor
        satisfies.  Returns False when the manifest is stale/duplicate."""
        if self.storage is None or not self.storage.wants_snapshot(manifest):
            return False
        from .block_store import WAL_ENTRY_SNAPSHOT

        self.wal_writer.write(WAL_ENTRY_SNAPSHOT, manifest.to_bytes())
        self.storage.adopt(manifest)
        leader = manifest.last_committed_leader
        if leader is not None and (
            leader.round > self.last_decided_leader.round
        ):
            self.last_decided_leader = AuthorityRound(
                leader.authority, leader.round
            )
        log.info(
            "adopted snapshot baseline: commit height %d, floor round %d",
            manifest.commit_height, manifest.gc_round,
        )
        # Transactions first shared below the floor are history we will
        # never process; the handler's oracles must expect their votes.
        self.block_handler.note_catchup(self.storage.retired_round)
        self._raise_dag_floor(self.storage.retired_round)
        if self.reconfig is not None and manifest.epoch_chain:
            # Cross-boundary catch-up: the manifest's epoch chain is the
            # rejoiner's only source for boundaries it slept through — adopt
            # it and switch onto the CURRENT committee before processing the
            # post-baseline block stream.
            transition = self.reconfig.adopt_chain(manifest.epoch_chain)
            if transition is not None:
                self._switch_epoch(transition)
        if self.execution is not None and manifest.exec_state:
            # The manifest's execution state is the rejoiner's only source
            # for the fold below the adopted baseline — without it the node
            # would re-root at genesis and disagree with the fleet forever.
            if self.execution.adopt(manifest.exec_state):
                log.info(
                    "adopted execution state: height %d, root %s",
                    self.execution.last_height, self.execution.root.hex()[:16],
                )
        return True

    def _raise_dag_floor(self, floor: RoundNumber) -> None:
        """Blocks parked on sub-floor parents release here; they enter the
        pipeline exactly as ``add_blocks`` would have entered them."""
        writer = BlockWriter(self.wal_writer, self.block_store)
        released, _missing = self.block_manager.set_gc_floor(floor, writer)
        if not released:
            return
        result = []
        for position, block in sorted(released, key=lambda pb: pb[1].round()):
            self.threshold_clock.add_block(block.reference, self.committee)
            self.pending.append((position, Include(block.reference)))
            result.append(block)
        self.run_block_handler(result)

    # -- maintenance --

    def cleanup(self) -> None:
        self.block_store.cleanup(
            max(0, self.last_decided_leader.round - self.store_retain_rounds)
        )
        if self.storage is not None:
            before = self.storage.retired_round
            self.storage.collect(self.block_store)
            if self.storage.retired_round > before:
                self._raise_dag_floor(self.storage.retired_round)
        self.block_handler.cleanup()

    def dag_floor(self) -> RoundNumber:
        """The round below which this store holds nothing (GC/adoption)."""
        return self.storage.retired_round if self.storage is not None else 0

    def commit_height(self) -> int:
        return self.storage.commit_height if self.storage is not None else 0

    def snapshot_manifest_for(self, peer_height: int):
        """Server side of snapshot catch-up: a manifest when the peer is far
        enough behind (and the knob is on), else None."""
        if self.storage is None or not self.storage.serves_snapshot_for(
            peer_height
        ):
            return None
        manifest = self.storage.build_manifest()
        if self.reconfig is not None:
            # The epoch chain rides the manifest so a rejoiner absent across
            # boundaries lands on the CURRENT committee, not the genesis one.
            manifest.epoch_chain = self.reconfig.chain.to_bytes()
        if self.execution is not None:
            # Likewise the execution state: the rejoiner lands on the
            # fleet's exact root instead of re-folding from genesis history
            # it no longer has.
            manifest.exec_state = self.execution.to_bytes()
        return manifest

    def wal_syncer(self) -> WalSyncer:
        return self.wal_writer.syncer()

    # -- accessors --

    def leaders(self, round_: RoundNumber) -> List[AuthorityIndex]:
        return self.committer.get_leaders(round_)

    def current_round(self) -> RoundNumber:
        return self.threshold_clock.get_round()

    def last_proposed(self) -> RoundNumber:
        return self.last_own_block.block.round()

    def last_own_block_value(self) -> StatementBlock:
        return self.last_own_block.block

    def epoch_closed(self) -> bool:
        return self.epoch_manager.closed()

    def epoch_changing(self) -> bool:
        return self.epoch_manager.changing()

    def epoch_closing_time(self) -> int:
        return self.epoch_manager.closing_time()
